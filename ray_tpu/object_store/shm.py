"""ctypes binding for the native shared-memory object store.

Builds ``libshm_store.so`` on first use (g++ is in the image; the build is
cached next to the source). ``get()`` returns a zero-copy memoryview over
the shared pages — numpy arrays deserialize without a copy, the plasma
property that matters for feeding TPU hosts.
"""

from __future__ import annotations

import collections
import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu.common.status import SpillFailedError

_SRC_DIR = os.path.join(os.path.dirname(__file__), "native")
_SO_PATH = os.path.join(_SRC_DIR, "libshm_store.so")
_build_lock = threading.Lock()
_lib = None


def _ensure_built() -> str:
    src = os.path.join(_SRC_DIR, "shm_store.cc")
    with _build_lock:
        if (not os.path.exists(_SO_PATH)
                or os.path.getmtime(_SO_PATH) < os.path.getmtime(src)):
            tmp = _SO_PATH + f".tmp.{os.getpid()}"
            subprocess.run(
                ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
                 "-o", tmp, src, "-lpthread", "-lrt"],
                check=True, capture_output=True)
            os.replace(tmp, _SO_PATH)  # atomic: concurrent builders race ok
    return _SO_PATH


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(_ensure_built())
    lib.rts_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.rts_create.restype = ctypes.c_int
    lib.rts_open.argtypes = [ctypes.c_char_p]
    lib.rts_open.restype = ctypes.c_int
    lib.rts_put.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32,
                            ctypes.c_char_p, ctypes.c_uint64]
    lib.rts_put.restype = ctypes.c_int
    lib.rts_get.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32,
                            ctypes.POINTER(ctypes.c_uint64)]
    lib.rts_get.restype = ctypes.POINTER(ctypes.c_ubyte)
    lib.rts_create_unsealed.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                        ctypes.c_uint32, ctypes.c_uint64]
    lib.rts_create_unsealed.restype = ctypes.POINTER(ctypes.c_ubyte)
    for name in ("rts_seal", "rts_abort"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32]
        fn.restype = ctypes.c_int
    for name in ("rts_release", "rts_contains", "rts_delete"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32]
        fn.restype = ctypes.c_int
    lib.rts_release_addr.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                     ctypes.c_uint32, ctypes.c_void_p]
    lib.rts_release_addr.restype = ctypes.c_int
    lib.rts_stats.argtypes = [ctypes.c_int] + \
        [ctypes.POINTER(ctypes.c_uint64)] * 3
    lib.rts_stats.restype = ctypes.c_int
    lib.rts_set_autoevict.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.rts_set_autoevict.restype = ctypes.c_int
    lib.rts_lru_candidate.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32)]
    lib.rts_lru_candidate.restype = ctypes.c_int
    lib.rts_lru_candidates.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_uint32, ctypes.c_uint64]
    lib.rts_lru_candidates.restype = ctypes.c_int
    lib.rts_unlink.argtypes = [ctypes.c_char_p]
    lib.rts_unlink.restype = ctypes.c_int
    lib.rts_close.argtypes = [ctypes.c_int]
    lib.rts_close.restype = ctypes.c_int
    _lib = lib
    return lib


# ------------------------------------------------------- spill engine

# Compressed spill file framing: raw (legacy) files carry no header;
# compressed files are  MAGIC | method byte | u64 raw_len | payload.
# The magic cannot collide with real payloads: spilled values are either
# pickle blobs (b"\x80...") or serialization frames (b"RTB5...").
_SPILL_MAGIC = b"RTSPL1"
_SPILL_METHODS: Dict[int, str] = {1: "zlib", 2: "lz4", 3: "zstd"}


def _resolve_codec(name: str):
    """``(method_byte, compress, decompress)`` for a codec name, or
    ``None`` for no compression.  lz4/zstd are optional deps — gated on
    import, with ``auto`` falling back lz4 → zstd → zlib (zlib is
    stdlib and always present)."""
    name = (name or "none").lower()
    if name in ("", "none", "0", "off"):
        return None
    if name in ("lz4", "auto"):
        try:
            import lz4.frame as _l4

            return (2, _l4.compress, _l4.decompress)
        except ImportError:
            if name == "lz4":
                raise ValueError("RT_spill_compression=lz4 but the lz4 "
                                 "package is not installed")
    if name in ("zstd", "auto"):
        try:
            import zstandard as _zs

            cctx, dctx = _zs.ZstdCompressor(level=1), _zs.ZstdDecompressor()
            return (3, cctx.compress,
                    lambda b, _d=dctx: _d.decompress(b))
        except ImportError:
            if name == "zstd":
                raise ValueError("RT_spill_compression=zstd but the "
                                 "zstandard package is not installed")
    if name in ("zlib", "auto"):
        import zlib as _zl

        return (1, lambda b: _zl.compress(b, 1), _zl.decompress)
    raise ValueError(f"unknown RT_spill_compression {name!r}")


def _decompress_spill(blob: bytes) -> bytes:
    """Decode a spill file: framed-compressed or raw legacy bytes."""
    if len(blob) < 15 or blob[:6] != _SPILL_MAGIC:
        return blob
    method = _SPILL_METHODS.get(blob[6])
    import struct as _struct

    raw_len = _struct.unpack_from("<Q", blob, 7)[0]
    payload = blob[15:]
    if method == "zlib":
        import zlib as _zl

        out = _zl.decompress(payload)
    elif method == "lz4":
        import lz4.frame as _l4

        out = _l4.decompress(payload)
    elif method == "zstd":
        import zstandard as _zs

        out = _zs.ZstdDecompressor().decompress(payload,
                                                max_output_size=raw_len)
    else:
        raise SpillFailedError(f"spill file with unknown codec {blob[6]}")
    if len(out) != raw_len:
        raise SpillFailedError(
            f"spill decompress length mismatch: {len(out)} != {raw_len}")
    return out


_spill_metrics = None
_spill_metrics_lock = threading.Lock()


def _metrics():
    """Process-wide spill counters (util/metrics; surfaced through the
    workers' metric push + raylet debug_state)."""
    global _spill_metrics
    if _spill_metrics is None:
        with _spill_metrics_lock:
            if _spill_metrics is None:
                from ray_tpu.util import metrics as M

                _spill_metrics = {
                    "spilled": M.Counter(
                        "rt_spill_bytes_spilled",
                        "bytes demoted to the spill dir (pre-compression)"),
                    "written": M.Counter(
                        "rt_spill_bytes_written",
                        "bytes physically written to spill files"),
                    "restored": M.Counter(
                        "rt_spill_bytes_restored",
                        "bytes read back from spill files (post-decompress)"),
                    "pending_hits": M.Counter(
                        "rt_spill_pending_hits",
                        "reads served from the writer queue before the "
                        "disk write landed"),
                    "prefetch_hits": M.Counter(
                        "rt_spill_prefetch_hits",
                        "restores served from the readahead cache"),
                    "prefetch_misses": M.Counter(
                        "rt_spill_prefetch_misses",
                        "restores that had to touch disk"),
                    "failures": M.Counter(
                        "rt_spill_failures", "failed spill writes"),
                    "dropped": M.Counter(
                        "rt_spill_files_dropped",
                        "spill files unlinked (batched)"),
                    "queue_depth": M.Gauge(
                        "rt_spill_writer_queue_depth",
                        "objects waiting in the spill writer queue"),
                    "queue_bytes": M.Gauge(
                        "rt_spill_writer_queue_bytes",
                        "bytes waiting in the spill writer queue"),
                }
    return _spill_metrics


class _SpillEngine:
    """Async spill I/O for one spill dir: a dedicated writer thread takes
    demotions off the caller's thread (the putting worker used to pay a
    synchronous open+write+rename per victim), a reader thread services
    announced-order readahead into a bounded cache, and unlinks batch.

    Correctness contract: a value handed to :meth:`submit` is readable
    via :meth:`read` from that moment on — first from the in-memory
    pending map, then from the file once the writer lands it.  A failed
    write KEEPS the bytes in the pending map (never lose the primary
    copy) and surfaces as a typed :class:`SpillFailedError` on the next
    spill operation.  All blocking I/O lives on the two engine threads —
    plain daemon threads, so the rt-analyze loop-blocker pass stays
    clean by construction (nothing here runs on an event loop).

    Known trade (measured, accepted): the pending map is PROCESS-LOCAL
    while the spill dir and arena are node-shared — between a demotion
    and its write landing, OTHER processes cannot see the value (arena
    copy deleted, file absent) and fall back to the owner-fetch path.
    The old synchronous write had no such window but serialized every
    demotion onto the putting thread (the round-12 headline cost).  The
    window is bounded by the queue byte cap (RT_spill_queue_mb,
    backpressure above it), close() drains synchronously if the writer
    can't, and only refcount-0 objects — ones no local reader holds —
    are ever demoted."""

    _UNLINK_BATCH = 64

    def __init__(self, spill_dir: str, path_of, on_first_spill=None):
        self._dir = spill_dir
        self._path_of = path_of          # oid -> file path
        self._on_first_spill = on_first_spill
        self._cv = threading.Condition()
        self._write_q: collections.deque = collections.deque()
        self._pending: Dict[bytes, bytes] = {}
        self._pending_bytes = 0
        self._failed_oids: set = set()   # pending writes that errored
        self._drops: List[str] = []
        self._prefetch_q: collections.deque = collections.deque()
        self._cache: "collections.OrderedDict[bytes, bytes]" = \
            collections.OrderedDict()
        self._cache_bytes = 0
        self._failed: Optional[BaseException] = None
        self._stop = False
        self._writer: Optional[threading.Thread] = None
        self._reader: Optional[threading.Thread] = None
        self._max_pending = int(os.environ.get(
            "RT_spill_queue_mb", "256")) << 20
        self._cache_cap = int(os.environ.get(
            "RT_spill_prefetch_mb", "64")) << 20
        self._codec = _resolve_codec(os.environ.get(
            "RT_spill_compression", "none"))
        self._stats = collections.Counter()
        self._tmp_seq = 0  # per-attempt tmp-file uniquifier

    # ------------------------------------------------------------ submit
    def _ensure_writer_locked(self) -> None:
        if self._writer is None or not self._writer.is_alive():
            self._writer = threading.Thread(
                target=self._write_loop, daemon=True, name="rt-spill-writer")
            self._writer.start()

    def _raise_if_failed_locked(self) -> None:
        # STICKY: once a write failed, every later spill op raises.  The
        # failed bytes stay parked in the pending map (readable, never
        # lost) — clearing the flag would let a submit block forever in
        # the backpressure wait against a queue that can no longer drain.
        if self._failed is not None:
            raise SpillFailedError(
                f"spill write to {self._dir} failed: "
                f"{self._failed}") from self._failed

    def submit(self, oid: bytes, data: bytes) -> None:
        """Queue `data` for durable write under `oid`'s spill path.
        Blocks while the queue is over its byte bound (backpressure on
        the demoting putter); raises SpillFailedError if a previous
        write failed (the failed bytes stay readable in-memory)."""
        data = bytes(data)
        with self._cv:
            self._raise_if_failed_locked()
            while (self._pending_bytes > self._max_pending
                   and self._failed is None and not self._stop):
                self._cv.wait(0.5)
            self._raise_if_failed_locked()
            if oid in self._pending:
                return  # already queued (idempotent)
            self._pending[oid] = data
            self._pending_bytes += len(data)
            self._write_q.append(oid)
            self._ensure_writer_locked()
            self._cv.notify_all()
        m = _metrics()
        m["spilled"].inc(len(data))
        m["queue_depth"].set(len(self._write_q))
        m["queue_bytes"].set(self._pending_bytes)

    # ------------------------------------------------------------- write
    def _write_one(self, oid: bytes, data: bytes) -> None:
        # injected OSError rides the write loop's failure handling: the
        # engine goes sticky-failed, the bytes stay readable in pending
        from ray_tpu.common import faults
        faults.fault_point("spill.write")
        payload = data
        if self._codec is not None:
            import struct as _struct

            method, comp, _ = self._codec
            body = comp(data)
            if len(body) < len(data):  # only keep wins
                payload = (_SPILL_MAGIC + bytes([method])
                           + _struct.pack("<Q", len(data)) + body)
        path = self._path_of(oid)
        # unique per ATTEMPT, pid kept last for the GC's stale-fragment
        # regex: the writer thread and a close()-time drain_sync may both
        # write (different oids normally, but never share a tmp path —
        # two threads truncating one tmp under each other interleaves
        # bytes into the durable file)
        with self._cv:
            self._tmp_seq += 1
            seq = self._tmp_seq
        tmp = f"{path}.{seq}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
        self._stats["bytes_written"] += len(payload)
        self._stats["bytes_spilled"] += len(data)
        _metrics()["written"].inc(len(payload))

    def _write_loop(self) -> None:
        first = True
        while True:
            with self._cv:
                while (not self._write_q and not self._drops
                       and not self._stop):
                    self._cv.wait(0.2)
                    if not self._write_q and self._drops:
                        break  # idle: flush the unlink batch
                if self._stop and not self._write_q and not self._drops:
                    return
                oid = self._write_q.popleft() if self._write_q else None
                data = self._pending.get(oid) if oid is not None else None
                drops, self._drops = (self._drops, []) \
                    if (len(self._drops) >= self._UNLINK_BATCH
                        or not self._write_q) else (None, self._drops)
            if drops:
                for p in drops:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
                self._stats["files_dropped"] += len(drops)
                _metrics()["dropped"].inc(len(drops))
            if oid is None or data is None:
                continue  # dropped while queued
            try:
                self._write_one(oid, data)
            except OSError as e:
                with self._cv:
                    self._failed = e
                    self._failed_oids.add(oid)  # bytes stay readable
                    self._cv.notify_all()
                self._stats["write_failures"] += 1
                _metrics()["failures"].inc()
                continue
            done = False
            with self._cv:
                if self._pending.pop(oid, None) is not None:
                    self._pending_bytes -= len(data)
                    done = True
                else:
                    # drop() cancelled the pending entry WHILE the write
                    # was in flight: the file just landed for a freed
                    # object — unlink it, or it leaks until session GC
                    # (and contains_spilled keeps answering True).
                    # Object ids are never reused after a free, so a
                    # later write under this oid cannot race the unlink.
                    self._drops.append(self._path_of(oid))
                self._cv.notify_all()
            if done and first and self._on_first_spill is not None:
                first = False
                try:
                    self._on_first_spill()
                except Exception:  # noqa: BLE001
                    pass
            m = _metrics()
            m["queue_depth"].set(len(self._write_q))
            m["queue_bytes"].set(self._pending_bytes)

    # -------------------------------------------------------------- read
    def read(self, oid: bytes) -> Optional[bytes]:
        with self._cv:
            data = self._pending.get(oid)
            if data is not None:
                self._stats["pending_hits"] += 1
                _metrics()["pending_hits"].inc()
                return data
            cached = self._cache.pop(oid, None)
            if cached is not None:
                self._cache_bytes -= len(cached)
                self._stats["prefetch_hits"] += 1
                _metrics()["prefetch_hits"].inc()
                return cached
        try:
            with open(self._path_of(oid), "rb") as f:
                blob = f.read()
        except OSError:
            return None
        out = _decompress_spill(blob)
        self._stats["prefetch_misses"] += 1
        self._stats["bytes_restored"] += len(out)
        m = _metrics()
        m["prefetch_misses"].inc()
        m["restored"].inc(len(out))
        return out

    def contains(self, oid: bytes) -> bool:
        with self._cv:
            return oid in self._pending or oid in self._cache

    # -------------------------------------------------------------- drop
    def cancel_pending(self, oid: bytes) -> bool:
        """Remove a queued-but-unwritten value (and any cached restore).
        True when the write was cancelled — no file will exist."""
        with self._cv:
            cached = self._cache.pop(oid, None)
            if cached is not None:
                self._cache_bytes -= len(cached)
            data = self._pending.pop(oid, None)
            if data is None:
                return False
            self._pending_bytes -= len(data)
            self._failed_oids.discard(oid)
            self._cv.notify_all()
            return True

    def drop(self, oid: bytes) -> None:
        """Batched unlink of `oid`'s spill file (the per-free unlink(2)
        was the hottest syscall of the small-task loop; the writer
        thread now takes them in batches)."""
        if self.cancel_pending(oid):
            return
        with self._cv:
            self._drops.append(self._path_of(oid))
            self._ensure_writer_locked()
            self._cv.notify_all()

    # ---------------------------------------------------------- prefetch
    def prefetch(self, oids) -> None:
        """Announced restore order: read the named spill files ahead of
        demand into a bounded cache (reads on the engine reader thread,
        never the caller's)."""
        with self._cv:
            for oid in oids:
                oid = bytes(oid)
                if oid in self._pending or oid in self._cache:
                    continue
                self._prefetch_q.append(oid)
            if self._prefetch_q and (self._reader is None
                                     or not self._reader.is_alive()):
                self._reader = threading.Thread(
                    target=self._read_loop, daemon=True,
                    name="rt-spill-reader")
                self._reader.start()
            self._cv.notify_all()

    def _read_loop(self) -> None:
        while True:
            with self._cv:
                while not self._prefetch_q and not self._stop:
                    self._cv.wait(0.2)
                if self._stop:
                    return
                oid = self._prefetch_q.popleft()
                if oid in self._pending or oid in self._cache:
                    continue
            try:
                with open(self._path_of(oid), "rb") as f:
                    blob = f.read()
            except OSError:
                continue  # not spilled (still resident) — nothing to do
            out = _decompress_spill(blob)
            with self._cv:
                if oid not in self._cache:
                    self._cache[oid] = out
                    self._cache_bytes += len(out)
                    while self._cache_bytes > self._cache_cap and \
                            len(self._cache) > 1:
                        _, old = self._cache.popitem(last=False)
                        self._cache_bytes -= len(old)

    # ------------------------------------------------------------- admin
    def flush(self, timeout: Optional[float] = 10.0) -> bool:
        """Wait until every queued write is durable (failed writes keep
        their bytes pending and do NOT block the flush — they are
        surfaced via SpillFailedError instead)."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._cv:
            while True:
                live = [o for o in self._pending
                        if o not in self._failed_oids]
                if not self._write_q and not live and not self._drops:
                    return True  # nothing queued: no thread ever starts
                if deadline is not None and _time.monotonic() >= deadline:
                    return False
                self._ensure_writer_locked()
                self._cv.notify_all()
                self._cv.wait(0.2)

    def drain_sync(self) -> None:
        """Last-resort durability on close: write every still-pending
        value INLINE on the calling thread (the writer thread may be
        wedged or too slow for the flush window — losing the bytes is
        worse than one synchronous exit-path write)."""
        while True:
            with self._cv:
                left = [(o, d) for o, d in self._pending.items()
                        if o not in self._failed_oids]
                if not left:
                    return
                oid, data = left[0]
            try:
                self._write_one(oid, data)
            except OSError as e:
                with self._cv:
                    self._failed = self._failed or e
                    self._failed_oids.add(oid)
                continue
            with self._cv:
                if self._pending.pop(oid, None) is not None:
                    self._pending_bytes -= len(data)
                self._cv.notify_all()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()

    def stats(self) -> dict:
        with self._cv:
            out = {"bytes_spilled": 0, "bytes_written": 0,
                   "bytes_restored": 0, "pending_hits": 0,
                   "prefetch_hits": 0, "prefetch_misses": 0,
                   "write_failures": 0, "files_dropped": 0}
            out.update(self._stats)
            out.update(
                queue_depth=len(self._write_q),
                queue_bytes=self._pending_bytes,
                prefetch_cache_bytes=self._cache_bytes,
                prefetch_queue=len(self._prefetch_q),
                drop_backlog=len(self._drops),
                failed=repr(self._failed) if self._failed else None,
                compression=(None if self._codec is None
                             else _SPILL_METHODS[self._codec[0]]),
            )
            written = out.get("bytes_written", 0)
            spilled = out.get("bytes_spilled", 0)
            out["compression_ratio"] = (
                round(written / spilled, 4) if spilled else None)
            return out


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True
    return True


def gc_spill_dirs(base: Optional[str] = None) -> dict:
    """Session-shutdown GC: remove spill state orphaned by dead
    processes — whole ``rt_spill_*`` dirs whose recorded owner pid is
    gone, ``rtshm_spill_*`` dirs whose arena segment no longer exists,
    and stale ``*.tmp.<pid>`` write fragments from crashed writers in
    any surviving dir.  Live sessions are never touched (owner-pid and
    segment-existence checks), so concurrent sessions sharing the same
    base dir are safe."""
    import re
    import shutil
    import tempfile

    if base is None:
        # the configured spilling dir may come from GLOBAL_CONFIG
        # (set_system_config_value) without the RT_ env var being set —
        # scanning only the env fallback would miss every orphan under
        # the configured location
        try:
            from ray_tpu.common.config import GLOBAL_CONFIG

            base = GLOBAL_CONFIG.get("object_spilling_dir") or None
        except Exception:  # noqa: BLE001 — standalone use of this module
            base = None
    base = base or os.environ.get("RT_object_spilling_dir") or \
        tempfile.gettempdir()
    removed = {"dirs": 0, "tmp_fragments": 0}
    try:
        names = os.listdir(base)
    except OSError:
        return removed
    for name in names:
        if not (name.startswith("rt_spill_")
                or name.startswith("rtshm_spill_")):
            continue
        path = os.path.join(base, name)
        if not os.path.isdir(path):
            continue
        if name.startswith("rtshm_spill_"):
            seg = "/dev/shm/" + name[len("rtshm_spill_"):]
            if os.path.isdir("/dev/shm") and not os.path.exists(seg):
                shutil.rmtree(path, ignore_errors=True)
                removed["dirs"] += 1
                continue
        else:
            owner = os.path.join(path, ".owner")
            try:
                with open(owner) as f:
                    pid = int(f.read().strip())
            except (OSError, ValueError):
                pid = None
            if pid is not None and not _pid_alive(pid):
                shutil.rmtree(path, ignore_errors=True)
                removed["dirs"] += 1
                continue
        # surviving dir: sweep write fragments left by dead processes
        try:
            entries = os.listdir(path)
        except OSError:
            continue
        for f in entries:
            m = re.search(r"\.tmp\.(\d+)$", f)
            if m and not _pid_alive(int(m.group(1))):
                try:
                    os.unlink(os.path.join(path, f))
                    removed["tmp_fragments"] += 1
                except OSError:
                    pass
    return removed


class ShmObjectStore:
    """One node-local store; any process opening the same name shares it."""

    # sentinel: derive the spill dir from the segment name (the default —
    # spill-before-evict is a SHARED-ARENA invariant, so every handle to
    # a segment must agree on it; pass spill_dir=None explicitly for a
    # pure-LRU store, e.g. unit tests of eviction itself)
    DERIVE = object()

    def __init__(self, name: str, capacity: int = 256 * 1024 * 1024,
                 create: bool = True, spill_dir=DERIVE):
        import tempfile

        self._lib = _load()
        self.name = name.encode() if isinstance(name, str) else name
        if spill_dir is ShmObjectStore.DERIVE:
            spill_dir = self._derived_spill_dir(self.name)
        if create:
            h = self._lib.rts_create(self.name, capacity)
        else:
            h = self._lib.rts_open(self.name)
        if h < 0:
            raise OSError(-h, f"shm store {name!r}: {os.strerror(-h)}")
        self._h = h
        # liveness cell shared with get_pinned finalizers: once close()
        # flips it, stale finalizers become no-ops instead of releasing
        # by address against whatever NEW arena reused this handle slot
        self._alive = [True]
        # pins taken via get(): id -> mapped addresses, so release() can
        # name the exact span even after a delete + re-put of the id
        self._pins: dict = {}
        self._pins_lock = threading.Lock()
        # spill-before-evict (plasma's SpillObjects contract): with a
        # spill dir, a full arena demotes LRU victims to node-local disk
        # instead of silently dropping primary copies — the round-5 fix
        # for GB-scale shuffles losing blocks once the working set passed
        # the arena size.  All processes on the node share the dir (it is
        # derived from the segment name), so any process can spill and
        # any process can read back.
        self._spill_dir = spill_dir
        # drop_spilled() runs on EVERY owned-ref free — an unconditional
        # unlink(2) there costs ~60 µs per freed object (measured: the
        # single hottest syscall of the small-task hot loop). The dir-level
        # sentinel below makes the no-spills-ever case free: it is created
        # on the first spill by ANY process sharing the dir, and each
        # handle re-checks it at most once a second until seen.
        self._spill_seen = False
        self._spill_seen_t = 0.0
        # async spill engine: demotions hand their bytes to a dedicated
        # writer thread (with optional compression and batched unlinks)
        # instead of paying a synchronous open+write+rename on the
        # putting thread; restores ride a readahead cache fed by the
        # consumer's announced order (prefetch_spilled)
        self._engine: Optional[_SpillEngine] = None
        self._spill_batch = max(1, int(os.environ.get("RT_spill_batch",
                                                      "8")))
        # demotion observer (object location directory: an arena copy
        # just became a spill-file copy) — must never fail a demotion
        self._demote_cb = None
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
            self._lib.rts_set_autoevict(self._h, 0)
            self._engine = _SpillEngine(spill_dir, self._spill_path,
                                        on_first_spill=self._mark_spilled)

    # ------------------------------------------------------ spill-on-evict
    @staticmethod
    def _derived_spill_dir(name: bytes) -> str:
        """ONE rule for segment-name → spill-dir, shared by every handle
        AND by unlink() — a mismatch silently splits the arena's durable
        copies across directories."""
        import tempfile

        base = os.environ.get("RT_object_spilling_dir") or \
            tempfile.gettempdir()
        return os.path.join(base,
                            "rtshm_spill_" + name.decode().lstrip("/"))

    def _can_ever_fit(self, size: int) -> bool:
        """Guard the demotion loop: an object bigger than the whole arena
        would otherwise flush every resident object to disk and STILL
        fail."""
        cap, _, _ = self.stats()
        return size <= cap

    def _spill_path(self, object_id: bytes) -> str:
        return os.path.join(self._spill_dir, object_id.hex())

    def _sentinel_path(self) -> str:
        return os.path.join(self._spill_dir, ".has_spills")

    def _mark_spilled(self) -> None:
        if not self._spill_seen:
            self._spill_seen = True
            try:
                open(self._sentinel_path(), "a").close()
            except OSError:
                pass

    def _maybe_has_spills(self) -> bool:
        """Cheap gate for per-free spill-file cleanup: False until any
        process sharing this spill dir has spilled (re-stat ≤ 1/s). The
        ≤1 s race can only leak a stray spill file until session teardown
        removes the dir — never lose data (read paths are unguarded)."""
        if self._spill_seen:
            return True
        import time as _time

        now = _time.monotonic()
        if now - self._spill_seen_t < 1.0:
            return False
        self._spill_seen_t = now
        self._spill_seen = os.path.exists(self._sentinel_path())
        return self._spill_seen

    def set_demote_callback(self, cb) -> None:
        """``cb(object_id: bytes)`` fires after a value this handle
        demoted becomes spill-backed (LRU demotion or direct
        put_or_spill overflow).  Used by the hosting worker to move the
        object's directory entry from arena-location to spill-location
        so remote pullers take the spill-streaming path."""
        self._demote_cb = cb

    def _notify_demoted(self, object_id: bytes) -> None:
        cb = self._demote_cb
        if cb is not None:
            try:
                cb(bytes(object_id))
            except Exception:  # noqa: BLE001 — observer must not fail spill
                pass

    def _spill_some(self, need_bytes: int = 0) -> bool:
        """Demote a BATCH of LRU victims to the async spill engine.
        ``need_bytes`` bounds the batch (0 = one batch of up to
        RT_spill_batch victims).  False when nothing was evictable.

        Per victim: copy the bytes out of the arena (one memcpy), hand
        them to the writer queue (readable from that instant), then free
        the span.  The demoting putter pays memcpy + enqueue instead of
        a synchronous disk write; victim selection is ONE native call
        and one lock acquisition for the whole batch."""
        n = self._spill_batch
        out_ids = ctypes.create_string_buffer(32 * n)
        out_lens = (ctypes.c_uint32 * n)()
        got = self._lib.rts_lru_candidates(self._h, out_ids, out_lens, n,
                                           max(0, need_bytes))
        if got <= 0:
            return False
        demoted_any = False
        for i in range(got):
            oid = out_ids.raw[i * 32:i * 32 + out_lens[i]]
            view = self.get(oid)
            if view is None:
                demoted_any = True  # raced with a delete: space freed
                continue
            try:
                data = bytes(view)
            finally:
                del view
                self.release(oid)
            # enqueue BEFORE deleting the arena copy: reads find the
            # bytes in the pending map the moment the span is gone
            self._engine.submit(oid, data)
            self._lib.rts_delete(self._h, oid, len(oid))
            self._notify_demoted(oid)
            demoted_any = True
        return demoted_any

    def _spill_one(self) -> bool:
        """Back-compat shim: demote (at least) the LRU victim."""
        return self._spill_some(1)

    def put_or_spill(self, object_id: bytes, data) -> bool:
        """Node-durable put: into the arena if it fits (after demoting LRU
        victims), else straight to the node spill dir.  Either way the
        bytes survive this PROCESS — the property primary copies of task
        returns need (the holding worker may be idle-reaped long before
        the owner fetches; reference: plasma holds primary copies in the
        store daemon, not in workers).  A refused spill write raises a
        typed :class:`SpillFailedError` — never a silent loss."""
        if self._spill_dir is None:
            return self.put(object_id, data)
        try:
            return self.put(object_id, data)
        except SpillFailedError:
            raise
        except OSError:
            pass  # nothing evictable (all pinned): demote THIS value
        if not isinstance(data, (bytes, bytearray, memoryview)):
            data = bytes(data)
        self._engine.submit(object_id, bytes(data))
        self._notify_demoted(object_id)
        return True

    def read_spilled(self, object_id: bytes) -> Optional[bytes]:
        """Bytes of a demoted object, or None.  Served from the writer
        queue while the write is in flight, from the readahead cache
        when the consumer announced its order, else one disk read (with
        transparent decompression).  The copy is NOT re-admitted
        (re-admission would immediately re-trigger pressure — the
        reference restores lazily too)."""
        if self._spill_dir is None:
            return None
        return self._engine.read(object_id)

    def drop_spilled(self, object_id: bytes) -> None:
        if self._spill_dir is None:
            return
        # a queued-but-unwritten value cancels for free (dict pop) —
        # checked before the dir-level sentinel gate, which only guards
        # the on-disk case
        if self._engine.cancel_pending(object_id):
            return
        if not self._maybe_has_spills():
            return
        self._engine.drop(object_id)

    def contains_spilled(self, object_id: bytes) -> bool:
        if self._spill_dir is None:
            return False
        return (self._engine.contains(object_id)
                or os.path.exists(self._spill_path(object_id)))

    def prefetch_spilled(self, object_ids) -> None:
        """Announce upcoming restore order: the engine's reader thread
        loads those spill files into its cache ahead of the reads."""
        if self._spill_dir is not None:
            self._engine.prefetch(object_ids)

    def flush_spills(self, timeout: Optional[float] = 10.0) -> bool:
        """Block until queued spill writes are durable (process-exit
        path: put_or_spill's survive-this-process contract)."""
        return self._engine.flush(timeout) if self._engine else True

    def spill_stats(self) -> dict:
        return self._engine.stats() if self._engine else {}

    def put(self, object_id: bytes, data) -> bool:
        """False if it already exists; raises on out-of-space."""
        if not isinstance(data, bytes):
            data = bytes(data)
        rc = self._lib.rts_put(self._h, object_id, len(object_id), data,
                               len(data))
        while rc == -28 and self._spill_dir is not None \
                and self._can_ever_fit(len(data)):  # ENOSPC
            if not self._spill_some(len(data)):
                break
            rc = self._lib.rts_put(self._h, object_id, len(object_id),
                                   data, len(data))
        if rc == 0:
            return True
        if rc == -17:      # EEXIST
            return False
        raise OSError(-rc, f"shm put failed: {os.strerror(-rc)}")

    def create(self, object_id: bytes, size: int) -> Optional[memoryview]:
        """Two-phase write (plasma CreateObject): a WRITABLE view over a
        freshly allocated arena span — serialize directly into it, then
        :meth:`seal`. None if the id exists or space can't be found.
        Unsealed entries are invisible to readers and to eviction."""
        while True:
            ptr = self._lib.rts_create_unsealed(self._h, object_id,
                                                len(object_id), size)
            if ptr:
                break
            # nullptr is EEXIST *or* ENOSPC: distinguish, then spill
            if self._spill_dir is None or self.contains(object_id) \
                    or not self._can_ever_fit(size):
                return None
            if not self._spill_some(size):
                return None
        addr = ctypes.addressof(ptr.contents)
        return memoryview((ctypes.c_ubyte * size).from_address(addr)) \
            .cast("B")

    def seal(self, object_id: bytes) -> None:
        rc = self._lib.rts_seal(self._h, object_id, len(object_id))
        if rc != 0:
            raise OSError(-rc, f"shm seal failed: {os.strerror(-rc)}")

    def abort(self, object_id: bytes) -> None:
        """Free the span of a failed two-phase write."""
        self._lib.rts_abort(self._h, object_id, len(object_id))

    def get(self, object_id: bytes) -> Optional[memoryview]:
        """Zero-copy view, pinned until :meth:`release`."""
        size = ctypes.c_uint64()
        ptr = self._lib.rts_get(self._h, object_id, len(object_id),
                                ctypes.byref(size))
        if not ptr:
            return None
        addr = ctypes.addressof(ptr.contents)
        with self._pins_lock:
            self._pins.setdefault(bytes(object_id), []).append(addr)
        return memoryview(
            (ctypes.c_ubyte * size.value).from_address(addr)).cast("B")

    def get_pinned(self, object_id: bytes) -> Optional[memoryview]:
        """Read-only zero-copy view whose pin releases ITSELF when the
        last alias dies (numpy arrays deserialized over the view keep
        the exporting ctypes object alive; a finalizer on it runs the
        release). This is the plasma property: objects stay pinned
        exactly while some Python buffer references them, and shared
        pages are immutable to readers. The release is by ADDRESS, so it
        stays correct even if the id is deleted and re-put while the
        view is alive."""
        import weakref

        size = ctypes.c_uint64()
        ptr = self._lib.rts_get(self._h, object_id, len(object_id),
                                ctypes.byref(size))
        if not ptr:
            return None
        addr = ctypes.addressof(ptr.contents)
        owner = (ctypes.c_ubyte * size.value).from_address(addr)

        def _release(lib=self._lib, h=self._h, oid=bytes(object_id),
                     a=addr, alive=self._alive):
            # guard against handle-slot reuse: after close() this handle
            # may name a DIFFERENT arena, and a by-address release there
            # would decrement an unrelated live object's pin
            if alive[0]:
                lib.rts_release_addr(h, oid, len(oid), a)

        weakref.finalize(owner, _release)
        return memoryview(owner).cast("B").toreadonly()

    def release(self, object_id: bytes) -> None:
        key = bytes(object_id)
        with self._pins_lock:
            addrs = self._pins.get(key)
            addr = addrs.pop() if addrs else None
            if addrs is not None and not addrs:
                del self._pins[key]
        if addr is not None:
            self._lib.rts_release_addr(self._h, object_id, len(object_id),
                                       addr)
        else:  # pin not taken through this wrapper: id-based best effort
            self._lib.rts_release(self._h, object_id, len(object_id))

    def contains(self, object_id: bytes) -> bool:
        return bool(self._lib.rts_contains(self._h, object_id,
                                           len(object_id)))

    def delete(self, object_id: bytes) -> bool:
        return self._lib.rts_delete(self._h, object_id, len(object_id)) == 0

    def stats(self) -> Tuple[int, int, int]:
        cap = ctypes.c_uint64()
        used = ctypes.c_uint64()
        num = ctypes.c_uint64()
        self._lib.rts_stats(self._h, ctypes.byref(cap), ctypes.byref(used),
                            ctypes.byref(num))
        return cap.value, used.value, num.value

    def close(self) -> None:
        """Unmap this process's view and free the handle slot for reuse.
        The shared segment (and other processes) are untouched. The
        per-process handle table is FIXED SIZE (64): a long-lived process
        that repeatedly opens arenas without closing them — e.g. a test
        harness init/shutdown-cycling the runtime — exhausts it and every
        later session silently loses its object plane. Pins still held by
        surviving views are abandoned (their finalizers are disarmed via
        the liveness cell, so slot reuse can never misroute a by-address
        release into a different arena)."""
        if self._engine is not None:
            # drain queued demotions first: their arena spans are gone,
            # so the pending bytes are the only copy until the writer
            # lands them (put_or_spill's survive-this-process contract).
            # A flush that can't finish in its window falls back to
            # synchronous inline writes — close() must not abandon the
            # only copy because the writer thread was slow or wedged.
            # stop() first lets the writer drain-and-exit (its loop only
            # returns on an empty queue); drain_sync then takes whatever
            # a wedged writer left (per-attempt tmp names make even a
            # still-running writer harmless).
            if not self._engine.flush(5.0):
                self._engine.stop()
                w = self._engine._writer
                if w is not None:
                    w.join(5.0)
                self._engine.drain_sync()
            self._engine.stop()
        self._alive[0] = False
        h, self._h = self._h, -1
        if h >= 0:
            self._lib.rts_close(h)

    def unlink(self):
        self._lib.rts_unlink(self.name)


def node_shm_name(node_id) -> str:
    """Canonical name of a node's arena segment — the ONE place the
    naming scheme lives (creator: the hosting raylet; openers: workers,
    stats, teardown in both deployment shapes)."""
    hexid = node_id if isinstance(node_id, str) else node_id.hex()
    return f"/rtshm_{hexid[:12]}"


def unlink(name) -> bool:
    """Unlink a segment by name WITHOUT opening it (no handle-slot cost).
    Also removes the segment's derived spill dir — demoted objects die
    with their arena (repeated sessions must not accumulate spilled GBs
    in /tmp)."""
    import shutil

    if isinstance(name, str):
        name = name.encode()
    shutil.rmtree(ShmObjectStore._derived_spill_dir(name),
                  ignore_errors=True)
    try:
        return _load().rts_unlink(name) == 0
    except Exception:  # noqa: BLE001 — lib unbuildable → nothing to unlink
        return False
