"""Device-resident objects: ``put``/``get`` for ``jax.Array``s that stay
in TPU HBM instead of round-tripping through host serialization.

Reference: ``python/ray/experimental/gpu_object_manager/``
(``gpu_object_manager.py:50`` GPUObjectManager, ``gpu_object_store.py``):
"tensor transport" for regular ``ray.put``/task args — tensors stay on
the producing worker's device, the owner triggers an out-of-band
transfer when a consumer on another worker needs them
(``trigger_out_of_band_tensor_transfer:183``).

TPU framing: there is no NCCL-style out-of-program p2p between separate
TPU processes — chip-to-chip ICI traffic exists only INSIDE compiled XLA
programs (collectives, compiled-graph channels). So the tiers are:

- same process: ``get`` returns the *same* ``jax.Array`` — zero copies,
  zero host traffic. This is the hot path for weight handoff between
  serve replicas'/trainers' components sharing a process and for
  driver-side reuse.
- cross process: the owner stages the value to host (``device_get``,
  DMA) and ships it through the ordinary zero-copy object plane (framed
  pickle-5 over shm/RPC); the consumer ``device_put``s onto its own
  chips. One host hop — the minimum physics allows between distinct
  TPU processes.
- in-program: for repeated tensor flow between pinned actors use
  compiled-graph :class:`~ray_tpu.graph.channels.DeviceBufferChannel` /
  XLA collectives; this module is the ad-hoc object path, not the
  pipeline path.

Values may be arbitrary pytrees; every ``jax.Array`` leaf stays on
device, other leaves ride along untouched.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class DeviceObjectMarker:
    """What the object plane stores/ships INSTEAD of the tensor bytes: a
    pointer to the process holding the device value plus shape/dtype
    metadata (reference: the GPU-object metadata travelling in place of
    the tensor, gpu_object_manager.py). Resolving a marker is the
    out-of-band transfer trigger."""

    object_id: bytes
    holder: Tuple[str, int]  # RPC address of the process with the value
    spec: Tuple  # ((shape, dtype), ...) of the array leaves


def _jax():
    import jax

    return jax


def is_device_value(value: Any) -> bool:
    """True if the value contains at least one jax.Array leaf (worth
    keeping on device)."""
    try:
        jax = _jax()
        leaves = jax.tree_util.tree_leaves(value)
    except Exception:  # noqa: BLE001 — jax unavailable/untreelike
        return False
    return any(isinstance(x, jax.Array) for x in leaves)


def spec_of(value: Any) -> List[Tuple[Tuple[int, ...], str]]:
    """(shape, dtype) of each array leaf — shipped in the marker so
    consumers can plan placement without fetching."""
    jax = _jax()
    return [(tuple(x.shape), str(x.dtype))
            for x in jax.tree_util.tree_leaves(value)
            if isinstance(x, jax.Array)]


class DeviceObjectStore:
    """Per-process map: object id -> device-resident pytree."""

    def __init__(self) -> None:
        self._objects: Dict[bytes, Any] = {}
        self._lock = threading.Lock()

    def put(self, object_id: bytes, value: Any) -> None:
        with self._lock:
            self._objects[object_id] = value

    def get(self, object_id: bytes) -> Optional[Any]:
        with self._lock:
            return self._objects.get(object_id)

    def contains(self, object_id: bytes) -> bool:
        with self._lock:
            return object_id in self._objects

    def free(self, object_id: bytes) -> None:
        with self._lock:
            self._objects.pop(object_id, None)

    def stage_to_host(self, object_id: bytes) -> Optional[Any]:
        """Owner-side out-of-band step: device arrays -> host numpy
        (single DMA per leaf), leaving the device copy in place. The
        result serializes through the zero-copy object plane."""
        with self._lock:
            value = self._objects.get(object_id)
        if value is None:
            return None
        jax = _jax()
        return jax.tree_util.tree_map(
            lambda x: jax.device_get(x) if isinstance(x, jax.Array) else x,
            value)

    def stats(self) -> Dict[str, int]:
        jax = _jax()
        with self._lock:
            vals = list(self._objects.values())
        nbytes = 0
        for v in vals:
            for leaf in jax.tree_util.tree_leaves(v):
                if isinstance(leaf, jax.Array):
                    nbytes += leaf.size * leaf.dtype.itemsize
        return {"num_objects": len(vals), "device_bytes": nbytes}


def restore_on_device(host_value: Any, device=None) -> Any:
    """Consumer-side: place staged host arrays onto this process's
    device(s). numpy leaves become jax.Arrays (matching what the
    producer held); non-array leaves pass through."""
    import numpy as np

    jax = _jax()
    kwargs = {"device": device} if device is not None else {}
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, **kwargs)
        if isinstance(x, np.ndarray) else x,
        host_value)
