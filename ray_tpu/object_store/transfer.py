"""Node-to-node object transfer service (reference: object_manager
push/pull).

One :class:`TransferServer` per raylet serves chunked reads of this
node's arena objects over a plain TCP socket: sealed objects stream
straight from the pinned arena view (``sendall`` over memoryview
slices — zero-copy on the holder, no pickle anywhere on the wire), and
spilled objects stream from their spill file without being restored
into the holder's arena.  The receiving side
(:func:`pull_object`) lands chunks directly into a create/seal arena
allocation — the zero-copy OOB put path extended across the wire — so a
cross-node fetch costs one wire copy into shared pages instead of a
pickle round-trip plus per-chunk owner RPCs through the owner's Python
loop (`h_get_object_chunk`, kept as the fallback/oracle path behind
``RT_transfer_service=0``).

Wire protocol (little-endian, fixed framing, connections are reusable):

    request  := b"RTX1" | u8 oid_len | oid bytes
    response := u8 status | u64 size | size raw bytes   (status 1 = hit)

All socket work is blocking and lives on dedicated daemon threads
(server: accept thread + thread per connection, the `_SpillEngine`
idiom) or is driven by callers from executor threads — nothing here may
run on an event loop.

Partial downloads are crash-safe: before landing into an unsealed arena
span the puller drops a ``<oid>.pull.<pid>`` marker next to the arena's
spill dir; :func:`gc_transfer_scratch` (session shutdown, the
``gc_spill_dirs`` owner-pid pattern) aborts spans whose puller died and
removes the markers.
"""

from __future__ import annotations

import os
import re
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

from ray_tpu.common import faults
from ray_tpu.common.config import GLOBAL_CONFIG
from ray_tpu.common.retry import Deadline
from ray_tpu.object_store.shm import (
    _SPILL_MAGIC,
    ShmObjectStore,
    _pid_alive,
    node_shm_name,
)

_MAGIC = b"RTX1"
_RESP = struct.Struct("<BQ")


class TransferError(OSError):
    """The holder broke mid-stream (died, closed, refused) — the caller
    should retry against another location or fall back to the owner."""


class TransferNotFound(KeyError):
    """The holder answered but no longer has the object (freed or
    demoted-and-collected between the directory read and the pull)."""


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF at a message boundary."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            return None if got == 0 else bytes(buf[:got])
        got += r
    return bytes(buf)


def _recv_into_exact(sock: socket.socket, view: memoryview, n: int,
                     chunk: int) -> None:
    """recv_into `view` until n bytes landed, reading at most `chunk`
    per call (bounds the kernel copy window; tests shrink it to force
    multi-chunk transfers)."""
    got = 0
    while got < n:
        want = min(chunk, n - got)
        r = sock.recv_into(view[got:got + want], want)
        if r == 0:
            raise TransferError(
                f"holder closed mid-stream at {got}/{n} bytes")
        got += r


class TransferServer:
    """Per-node socket server streaming this node's objects.

    The arena handle attaches lazily on the first request — the hosting
    raylet starts the server unconditionally, but a node that never
    holds a large object never maps the segment.
    """

    def __init__(self, node_id, host: str = "127.0.0.1",
                 store: Optional[ShmObjectStore] = None):
        self._node_id = node_id
        self._host = host
        self._store = store
        self._store_lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._stopped = False
        self.port: Optional[int] = None
        self.stats = {"requests": 0, "hits": 0, "spill_streams": 0,
                      "misses": 0}

    # ------------------------------------------------------------ lifecycle
    def start(self) -> Tuple[str, int]:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._host, 0))
        s.listen(128)
        self._sock = s
        self.port = s.getsockname()[1]
        t = threading.Thread(target=self._accept_loop,
                             name="rt-transfer-accept", daemon=True)
        t.start()
        return (self._host, self.port)

    @property
    def address(self) -> Tuple[str, int]:
        return (self._host, self.port)

    def stop(self) -> None:
        self._stopped = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._store_lock:
            store, self._store = self._store, None
        if store is not None:
            try:
                store.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    # --------------------------------------------------------------- store
    def _get_store(self) -> Optional[ShmObjectStore]:
        with self._store_lock:
            if self._store is None and not self._stopped:
                try:
                    self._store = ShmObjectStore(
                        node_shm_name(self._node_id),
                        capacity=GLOBAL_CONFIG.get("shm_store_bytes"))
                except OSError:
                    return None
            return self._store

    # --------------------------------------------------------------- serve
    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed by stop()
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="rt-transfer-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._stopped:
                hdr = _recv_exact(conn, len(_MAGIC) + 1)
                if hdr is None or len(hdr) < len(_MAGIC) + 1 \
                        or hdr[:len(_MAGIC)] != _MAGIC:
                    return
                oid = _recv_exact(conn, hdr[len(_MAGIC)])
                if oid is None:
                    return
                self.stats["requests"] += 1
                self._serve_one(conn, oid)
        except OSError:
            pass  # reader went away mid-stream; nothing to unwind
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_one(self, conn: socket.socket, oid: bytes) -> None:
        # Injected OSError propagates to _serve_conn's handler, which
        # drops the connection — the puller sees a dead holder (EOF
        # before reply), the exact signature of a mid-request crash.
        faults.fault_point("transfer.server.send")
        chunk = GLOBAL_CONFIG.get("transfer_chunk_bytes")
        store = self._get_store()
        view = store.get_pinned(oid) if store is not None else None
        if view is not None:
            try:
                conn.sendall(_RESP.pack(1, len(view)))
                for off in range(0, len(view), chunk):
                    conn.sendall(view[off:off + chunk])
            finally:
                del view  # finalizer drops the pin
            self.stats["hits"] += 1
            return
        if store is not None and store.contains_spilled(oid):
            if self._stream_spill_file(conn, store, oid, chunk):
                self.stats["spill_streams"] += 1
                return
            # compressed on disk or still in the writer queue:
            # read_spilled decompresses / serves the pending bytes —
            # still no arena re-admission on this node
            blob = store.read_spilled(oid)
            if blob is not None:
                conn.sendall(_RESP.pack(1, len(blob)))
                conn.sendall(blob)
                self.stats["spill_streams"] += 1
                return
        self.stats["misses"] += 1
        conn.sendall(_RESP.pack(0, 0))

    @staticmethod
    def _stream_spill_file(conn: socket.socket, store: ShmObjectStore,
                           oid: bytes, chunk: int) -> bool:
        """Stream an UNCOMPRESSED spill file straight from disk (the
        no-local-restore path). False when the file is compressed or
        not on disk yet — the caller falls back to read_spilled."""
        try:
            f = open(store._spill_path(oid), "rb")
        except OSError:
            return False
        with f:
            head = f.read(len(_SPILL_MAGIC))
            if head == _SPILL_MAGIC:
                return False  # compressed: needs read_spilled's codec
            size = os.fstat(f.fileno()).st_size
            conn.sendall(_RESP.pack(1, size))
            if head:
                conn.sendall(head)
            sent = len(head)
            while sent < size:
                data = f.read(min(chunk, size - sent))
                if not data:
                    raise TransferError("spill file truncated under us")
                conn.sendall(data)
                sent += len(data)
        return True


# ---------------------------------------------------------------- client

class _Pull:
    __slots__ = ("done", "result", "exc")

    def __init__(self):
        self.done = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None


_inflight: Dict[bytes, _Pull] = {}
_inflight_lock = threading.Lock()
stats = {"downloads": 0, "dedup_waits": 0}


def _marker_path(shm: Optional[ShmObjectStore],
                 oid: bytes) -> Optional[str]:
    d = getattr(shm, "_spill_dir", None) if shm is not None else None
    if not d:
        return None
    return os.path.join(d, f"{oid.hex()}.pull.{os.getpid()}")


def pull_object(address, object_id: bytes,
                shm: Optional[ShmObjectStore] = None,
                timeout: float = 30.0,
                deadline: Optional[Deadline] = None):
    """Fetch one object from a holder's transfer server.

    Returns a pinned read-only arena view when the bytes landed in the
    local arena (create/seal two-phase — same-process AND same-node
    readers then alias the shared pages), else an on-heap memoryview.
    Concurrent pulls of the same id in this process dedupe into ONE
    wire download; followers share the leader's landed view.

    ``deadline`` is the caller's REMAINING budget (common/retry.py);
    every wait in here — follower wait on the leader, connect, socket
    reads — is clipped to it, with ``timeout`` as the per-step cap.
    Without one, ``timeout`` alone bounds each step (the old contract).

    Raises :class:`TransferNotFound` (holder no longer has it) or
    :class:`TransferError` (holder died mid-stream / unreachable /
    budget exhausted) — the caller decides whether another location or
    the owner path is next.
    """
    if deadline is None:
        deadline = Deadline(timeout)
    with _inflight_lock:
        ent = _inflight.get(object_id)
        leader = ent is None
        if leader:
            ent = _inflight[object_id] = _Pull()
    if not leader:
        stats["dedup_waits"] += 1
        try:
            faults.fault_point("transfer.pull.dedup_wait")
        except faults.FaultInjected as e:
            raise TransferError(
                f"deduped pull of {object_id.hex()} from "
                f"{tuple(address)} failed: {e}") from e
        # Follower budget = the FOLLOWER's remaining deadline, not a
        # fixed window: a caller with 2 s left must not block 30 s on a
        # leader working someone else's clock.
        wait_s = deadline.remaining(cap=timeout)
        if not ent.done.wait(wait_s):
            raise TransferError(
                f"deduped pull of {object_id.hex()} from "
                f"{tuple(address)} timed out after {wait_s:.1f}s "
                f"(caller's remaining budget)")
        if ent.exc is not None:
            raise ent.exc
        return ent.result
    try:
        ent.result = _pull_once(tuple(address), object_id, shm, timeout,
                                deadline)
        return ent.result
    except BaseException as e:
        ent.exc = e
        raise
    finally:
        with _inflight_lock:
            _inflight.pop(object_id, None)
        ent.done.set()


def _pull_once(address, object_id: bytes, shm: Optional[ShmObjectStore],
               timeout: float, deadline: Deadline):
    stats["downloads"] += 1
    chunk = GLOBAL_CONFIG.get("transfer_chunk_bytes")
    # floor: an almost-spent budget must surface as a (typed) timeout,
    # never as timeout=0 ("blocking forever" to the socket module)
    budget = deadline.remaining(cap=timeout, floor=0.001)
    try:
        faults.fault_point("transfer.pull.connect")
        sock = socket.create_connection(address, timeout=budget)
    except OSError as e:
        raise TransferError(
            f"transfer server {address} unreachable: {e}") from e
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.sendall(_MAGIC + bytes([len(object_id)]) + object_id)
        faults.fault_point("transfer.pull.recv")
        hdr = _recv_exact(sock, _RESP.size)
        if hdr is None or len(hdr) < _RESP.size:
            raise TransferError(f"holder {address} closed before reply")
        status, size = _RESP.unpack(hdr)
        if status != 1:
            raise TransferNotFound(object_id.hex())
        return _land(sock, object_id, size, shm, chunk)
    except socket.timeout as e:
        raise TransferError(
            f"pull of {object_id.hex()} from {address} timed out "
            f"after {budget:.1f}s") from e
    except TransferError:
        raise
    except OSError as e:
        # torn connection / injected fault mid-pull: type it so callers
        # keep one contract (TransferError = try the next location)
        raise TransferError(
            f"pull of {object_id.hex()} from {address} failed: {e}") from e
    finally:
        sock.close()


def _land(sock: socket.socket, object_id: bytes, size: int,
          shm: Optional[ShmObjectStore], chunk: int):
    buf = None
    if shm is not None and size > 0:
        try:
            buf = shm.create(object_id, size)
        except OSError:
            buf = None
        if buf is None:
            # EEXIST: sealed copy already here (raced another process's
            # pull or a local seal) — just alias it
            existing = shm.get_pinned(object_id)
            if existing is not None and len(existing) == size:
                _drain(sock, size, chunk)
                return existing
    if buf is not None:
        marker = _marker_path(shm, object_id)
        if marker:
            try:
                open(marker, "a").close()
            except OSError:
                marker = None
        sealed = False
        try:
            _recv_into_exact(sock, buf, size, chunk)
            del buf  # drop the writable alias before sealing
            shm.seal(object_id)
            sealed = True
        finally:
            if not sealed:
                try:
                    shm.abort(object_id)
                except Exception:  # noqa: BLE001 — abort is best-effort
                    pass
            if marker:
                try:
                    os.unlink(marker)
                except OSError:
                    pass
        return shm.get_pinned(object_id)
    # no arena (disabled / full / unsized): land on heap, no extra copy
    data = bytearray(size)
    _recv_into_exact(sock, memoryview(data), size, chunk)
    return memoryview(data)


def _drain(sock: socket.socket, size: int, chunk: int) -> None:
    """Consume and discard a response body (duplicate-landing race) so
    the connection stays usable / closes cleanly."""
    sink = bytearray(min(chunk, size) or 1)
    left = size
    while left > 0:
        want = min(len(sink), left)
        r = sock.recv_into(memoryview(sink)[:want], want)
        if r == 0:
            return
        left -= r


# ------------------------------------------------------------------- GC

_MARKER_RE = re.compile(r"^([0-9a-f]+)\.pull\.(\d+)$")


def gc_transfer_scratch(base: Optional[str] = None) -> dict:
    """Reclaim partial-download scratch left by dead pullers: the
    ``<oid>.pull.<pid>`` markers written by :func:`pull_object` before
    landing into an unsealed arena span.  For each marker whose pid is
    dead, the span is aborted in the (shared, still-live) arena and the
    marker removed — the ``gc_spill_dirs`` owner-pid pattern applied to
    transfer temp state.  Spill dirs whose whole segment is gone are
    ``gc_spill_dirs``'s job, not ours."""
    import tempfile

    if base is None:
        base = os.environ.get("RT_object_spilling_dir") or \
            tempfile.gettempdir()
    removed = {"markers": 0, "aborted": 0}
    try:
        names = os.listdir(base)
    except OSError:
        return removed
    for name in names:
        if not name.startswith("rtshm_spill_"):
            continue
        path = os.path.join(base, name)
        try:
            entries = os.listdir(path)
        except OSError:
            continue
        dead = []
        for f in entries:
            m = _MARKER_RE.match(f)
            if m and not _pid_alive(int(m.group(2))):
                dead.append((f, m.group(1)))
        if not dead:
            continue
        store = None
        seg = "/" + name[len("rtshm_spill_"):]
        if os.path.exists("/dev/shm" + seg):
            try:
                store = ShmObjectStore(seg, create=False, spill_dir=None)
            except OSError:
                store = None
        try:
            for f, oid_hex in dead:
                if store is not None:
                    try:
                        store.abort(bytes.fromhex(oid_hex))
                        removed["aborted"] += 1
                    except Exception:  # noqa: BLE001 — sealed/raced: fine
                        pass
                try:
                    os.unlink(os.path.join(path, f))
                    removed["markers"] += 1
                except OSError:
                    pass
        finally:
            if store is not None:
                store.close()
    return removed
