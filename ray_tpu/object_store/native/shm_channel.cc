// Mutable shared-memory channel — compiled-graph transport.
//
// Reference: src/ray/core_worker/experimental_mutable_object_manager.cc and
// python/ray/experimental/channel/shared_memory_channel.py: a mutable
// plasma buffer with writer/reader semaphores; the writer rewrites the SAME
// buffer once every reader has consumed the previous version.
//
// Redesign (daemon-less, like shm_store.cc): one POSIX shm segment per
// channel holding a robust process-shared mutex + condvar, a version
// counter, a reader-ack counter, and the payload arena. Protocol:
//
//   write(buf):  lock; wait until acks == num_readers (previous value fully
//                consumed — this is the pipeline backpressure); memcpy in;
//                version++; acks = 0; broadcast.
//   read(last):  lock; wait until version > last; memcpy out; acks++;
//                broadcast; return version.
//
// Copies happen under the lock (payloads are pipeline activations, small
// relative to the RPC+pickle+scheduler path they replace). A crashed peer
// cannot wedge the channel: EOWNERDEAD recovery marks state consistent,
// and close() wakes all waiters with an error.
//
// Build: g++ -O2 -fPIC -shared -o libshm_channel.so shm_channel.cc -lpthread -lrt

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x525443484e4c3031ULL;  // "RTCHNL01"

struct ChannelHeader {
  uint64_t magic;
  uint64_t capacity;
  pthread_mutex_t mu;
  pthread_cond_t cv;
  uint64_t version;      // sequence number of the value in the arena
  uint64_t acks;         // readers that consumed `version`
  uint64_t num_readers;
  uint64_t len;          // payload bytes of current value
  int32_t closed;
  // arena follows
};

struct Handle {
  ChannelHeader* hdr;
  uint64_t map_size;
};

char* arena(ChannelHeader* h) {
  return reinterpret_cast<char*>(h) + sizeof(ChannelHeader);
}

int lock_robust(ChannelHeader* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    // previous owner died mid-critical-section; state is still a valid
    // snapshot (counters are only advanced after memcpy completes)
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

void deadline_after_ms(timespec* ts, int64_t ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += ms / 1000;
  ts->tv_nsec += (ms % 1000) * 1000000;
  if (ts->tv_nsec >= 1000000000) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000;
  }
}

constexpr int kMaxHandles = 4096;
Handle g_handles[kMaxHandles];
int g_next_handle = 0;
pthread_mutex_t g_handles_mu = PTHREAD_MUTEX_INITIALIZER;

}  // namespace

extern "C" {

// Returns handle >= 0, or -errno.
int rtc_create(const char* name, uint64_t capacity, uint64_t num_readers) {
  uint64_t map_size = sizeof(ChannelHeader) + capacity;
  int fd = shm_open(name, O_CREAT | O_RDWR, 0600);
  if (fd < 0) return -errno;
  struct stat st;
  bool fresh = fstat(fd, &st) == 0 && st.st_size == 0;
  if (fresh && ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return -errno;
  auto* hdr = static_cast<ChannelHeader*>(mem);
  if (fresh || hdr->magic != kMagic) {
    std::memset(hdr, 0, sizeof(ChannelHeader));
    hdr->capacity = capacity;
    hdr->num_readers = num_readers;
    pthread_mutexattr_t ma;
    pthread_mutexattr_init(&ma);
    pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hdr->mu, &ma);
    pthread_condattr_t ca;
    pthread_condattr_init(&ca);
    pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
    pthread_cond_init(&hdr->cv, &ca);
    __sync_synchronize();
    hdr->magic = kMagic;
  }
  pthread_mutex_lock(&g_handles_mu);
  int h = g_next_handle++;
  if (h >= kMaxHandles) {
    pthread_mutex_unlock(&g_handles_mu);
    munmap(mem, map_size);
    return -ENOMEM;
  }
  g_handles[h] = {hdr, map_size};
  pthread_mutex_unlock(&g_handles_mu);
  return h;
}

int rtc_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return -errno;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  void* mem = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return -errno;
  auto* hdr = static_cast<ChannelHeader*>(mem);
  if (hdr->magic != kMagic) {
    munmap(mem, st.st_size);
    return -EINVAL;
  }
  pthread_mutex_lock(&g_handles_mu);
  int h = g_next_handle++;
  if (h >= kMaxHandles) {
    pthread_mutex_unlock(&g_handles_mu);
    munmap(mem, st.st_size);
    return -ENOMEM;
  }
  g_handles[h] = {hdr, static_cast<uint64_t>(st.st_size)};
  pthread_mutex_unlock(&g_handles_mu);
  return h;
}

// 0 ok; -EAGAIN timeout; -EPIPE closed; -EMSGSIZE too big.
int rtc_write(int h, const char* data, uint64_t len, int64_t timeout_ms) {
  ChannelHeader* hdr = g_handles[h].hdr;
  if (len > hdr->capacity) return -EMSGSIZE;
  timespec ts;
  deadline_after_ms(&ts, timeout_ms);
  if (lock_robust(hdr) != 0) return -EINVAL;
  // wait for every reader to have consumed the previous version
  while (!hdr->closed && hdr->version != 0 && hdr->acks < hdr->num_readers) {
    if (pthread_cond_timedwait(&hdr->cv, &hdr->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&hdr->mu);
      return -EAGAIN;
    }
  }
  if (hdr->closed) {
    pthread_mutex_unlock(&hdr->mu);
    return -EPIPE;
  }
  std::memcpy(arena(hdr), data, len);
  hdr->len = len;
  hdr->version += 1;
  hdr->acks = 0;
  pthread_cond_broadcast(&hdr->cv);
  pthread_mutex_unlock(&hdr->mu);
  return 0;
}

// Blocks until version > last_version; copies payload into out (cap
// out_cap). Returns new version (>0); -EAGAIN timeout; -EPIPE closed;
// -EMSGSIZE out buffer too small (required size in *out_len).
int64_t rtc_read(int h, uint64_t last_version, char* out, uint64_t out_cap,
                 uint64_t* out_len, int64_t timeout_ms) {
  ChannelHeader* hdr = g_handles[h].hdr;
  timespec ts;
  deadline_after_ms(&ts, timeout_ms);
  if (lock_robust(hdr) != 0) return -EINVAL;
  while (!hdr->closed && hdr->version <= last_version) {
    if (pthread_cond_timedwait(&hdr->cv, &hdr->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&hdr->mu);
      return -EAGAIN;
    }
  }
  if (hdr->closed && hdr->version <= last_version) {
    pthread_mutex_unlock(&hdr->mu);
    return -EPIPE;
  }
  *out_len = hdr->len;
  if (hdr->len > out_cap) {
    pthread_mutex_unlock(&hdr->mu);
    return -EMSGSIZE;
  }
  std::memcpy(out, arena(hdr), hdr->len);
  uint64_t v = hdr->version;
  hdr->acks += 1;
  pthread_cond_broadcast(&hdr->cv);
  pthread_mutex_unlock(&hdr->mu);
  return static_cast<int64_t>(v);
}

uint64_t rtc_capacity(int h) { return g_handles[h].hdr->capacity; }

int rtc_close(int h) {
  ChannelHeader* hdr = g_handles[h].hdr;
  if (lock_robust(hdr) != 0) return -EINVAL;
  hdr->closed = 1;
  pthread_cond_broadcast(&hdr->cv);
  pthread_mutex_unlock(&hdr->mu);
  return 0;
}

int rtc_unlink(const char* name) {
  return shm_unlink(name) == 0 ? 0 : -errno;
}

}  // extern "C"
