// Mutable shared-memory channel — compiled-graph transport.
//
// Reference: src/ray/core_worker/experimental_mutable_object_manager.cc and
// python/ray/experimental/channel/shared_memory_channel.py: a mutable
// plasma buffer with writer/reader semaphores; the writer rewrites the SAME
// buffer once every reader has consumed the previous version.
//
// Redesign (daemon-less, like shm_store.cc): one POSIX shm segment per
// channel holding a robust process-shared mutex, a futex sequence word, a
// version counter, a reader-ack counter, and the payload arena. Protocol:
//
//   write(buf):  lock; wait until acks == num_readers (previous value fully
//                consumed — this is the pipeline backpressure); memcpy in;
//                version++; acks = 0; wake.
//   read(last):  lock; wait until version > last; memcpy out; acks++;
//                wake; return version.
//
// Copies happen under the lock (payloads are pipeline activations, small
// relative to the RPC+pickle+scheduler path they replace).
//
// Blocking is a raw futex on `seq` (bumped on every state change), NOT a
// process-shared pthread condvar: glibc pshared condvars keep waiter
// accounting (__wrefs/__g_refs) in the shared segment, and a peer
// SIGKILLed mid-wait leaks its reference forever — every later
// signal/broadcast then wedges in the group-quiesce spin, hanging all
// SURVIVING processes (observed: a killed RL env-runner froze the queue
// actor inside a zero-timeout read). Futex wait queues live in the
// kernel, keyed by task — a dead waiter simply evaporates. Combined with
// EOWNERDEAD recovery on the mutex (dead lock HOLDERS), a crashed peer
// cannot wedge the channel, and close() wakes all waiters with an error.
//
// Build: g++ -O2 -fPIC -shared -o libshm_channel.so shm_channel.cc -lpthread -lrt

#include <cerrno>
#include <climits>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <linux/futex.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x525443484e4c3032ULL;  // "RTCHNL02"

struct ChannelHeader {
  uint64_t magic;
  uint64_t capacity;
  pthread_mutex_t mu;
  uint32_t seq;          // futex word: state-change notification counter
  uint32_t seq_pad_;
  uint64_t version;      // sequence number of the value in the arena
  uint64_t acks;         // readers that consumed `version`
  uint64_t num_readers;
  uint64_t len;          // payload bytes of current value
  int32_t closed;
  // arena follows
};

struct Handle {
  ChannelHeader* hdr;
  uint64_t map_size;
};

char* arena(ChannelHeader* h) {
  return reinterpret_cast<char*>(h) + sizeof(ChannelHeader);
}

int lock_robust(ChannelHeader* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    // previous owner died mid-critical-section; state is still a valid
    // snapshot (counters are only advanced after memcpy completes)
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

void deadline_after_ms(timespec* ts, int64_t ms) {
  clock_gettime(CLOCK_MONOTONIC, ts);
  ts->tv_sec += ms / 1000;
  ts->tv_nsec += (ms % 1000) * 1000000;
  if (ts->tv_nsec >= 1000000000) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000;
  }
}

// Wait for `seq` to move past `seen`, bounded by the absolute MONOTONIC
// deadline.  Returns ETIMEDOUT at the deadline; 0 on wake / value-change
// / EINTR (the caller re-checks channel state under the lock either way).
int wait_seq(ChannelHeader* h, uint32_t seen, const timespec* deadline) {
  timespec now;
  clock_gettime(CLOCK_MONOTONIC, &now);
  timespec rel;
  rel.tv_sec = deadline->tv_sec - now.tv_sec;
  rel.tv_nsec = deadline->tv_nsec - now.tv_nsec;
  if (rel.tv_nsec < 0) {
    rel.tv_sec -= 1;
    rel.tv_nsec += 1000000000;
  }
  if (rel.tv_sec < 0 || (rel.tv_sec == 0 && rel.tv_nsec == 0)) {
    return ETIMEDOUT;
  }
  long rc = syscall(SYS_futex, &h->seq, FUTEX_WAIT, seen, &rel,
                    nullptr, 0);
  if (rc == -1 && errno == ETIMEDOUT) return ETIMEDOUT;
  return 0;
}

// Bump the sequence word and wake every waiter.  Call while holding the
// mutex so the bump is ordered against the state change it publishes.
void wake_all(ChannelHeader* h) {
  __atomic_fetch_add(&h->seq, 1, __ATOMIC_SEQ_CST);
  syscall(SYS_futex, &h->seq, FUTEX_WAKE, INT_MAX, nullptr, nullptr, 0);
}

constexpr int kMaxHandles = 4096;
Handle g_handles[kMaxHandles];
int g_next_handle = 0;
pthread_mutex_t g_handles_mu = PTHREAD_MUTEX_INITIALIZER;

}  // namespace

extern "C" {

// Returns handle >= 0, or -errno.
int rtc_create(const char* name, uint64_t capacity, uint64_t num_readers) {
  uint64_t map_size = sizeof(ChannelHeader) + capacity;
  int fd = shm_open(name, O_CREAT | O_RDWR, 0600);
  if (fd < 0) return -errno;
  struct stat st;
  bool fresh = fstat(fd, &st) == 0 && st.st_size == 0;
  if (fresh && ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return -errno;
  auto* hdr = static_cast<ChannelHeader*>(mem);
  if (fresh || hdr->magic != kMagic) {
    std::memset(hdr, 0, sizeof(ChannelHeader));
    hdr->capacity = capacity;
    hdr->num_readers = num_readers;
    pthread_mutexattr_t ma;
    pthread_mutexattr_init(&ma);
    pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hdr->mu, &ma);
    __sync_synchronize();
    hdr->magic = kMagic;
  }
  pthread_mutex_lock(&g_handles_mu);
  int h = g_next_handle++;
  if (h >= kMaxHandles) {
    pthread_mutex_unlock(&g_handles_mu);
    munmap(mem, map_size);
    return -ENOMEM;
  }
  g_handles[h] = {hdr, map_size};
  pthread_mutex_unlock(&g_handles_mu);
  return h;
}

int rtc_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return -errno;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  void* mem = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return -errno;
  auto* hdr = static_cast<ChannelHeader*>(mem);
  if (hdr->magic != kMagic) {
    munmap(mem, st.st_size);
    return -EINVAL;
  }
  pthread_mutex_lock(&g_handles_mu);
  int h = g_next_handle++;
  if (h >= kMaxHandles) {
    pthread_mutex_unlock(&g_handles_mu);
    munmap(mem, st.st_size);
    return -ENOMEM;
  }
  g_handles[h] = {hdr, static_cast<uint64_t>(st.st_size)};
  pthread_mutex_unlock(&g_handles_mu);
  return h;
}

// 0 ok; -EAGAIN timeout; -EPIPE closed; -EMSGSIZE too big.
int rtc_write(int h, const char* data, uint64_t len, int64_t timeout_ms) {
  ChannelHeader* hdr = g_handles[h].hdr;
  if (len > hdr->capacity) return -EMSGSIZE;
  timespec ts;
  deadline_after_ms(&ts, timeout_ms);
  if (lock_robust(hdr) != 0) return -EINVAL;
  // wait for every reader to have consumed the previous version
  while (!hdr->closed && hdr->version != 0 && hdr->acks < hdr->num_readers) {
    uint32_t seen = __atomic_load_n(&hdr->seq, __ATOMIC_SEQ_CST);
    pthread_mutex_unlock(&hdr->mu);
    if (wait_seq(hdr, seen, &ts) == ETIMEDOUT) return -EAGAIN;
    if (lock_robust(hdr) != 0) return -EINVAL;
  }
  if (hdr->closed) {
    pthread_mutex_unlock(&hdr->mu);
    return -EPIPE;
  }
  std::memcpy(arena(hdr), data, len);
  hdr->len = len;
  hdr->version += 1;
  hdr->acks = 0;
  wake_all(hdr);
  pthread_mutex_unlock(&hdr->mu);
  return 0;
}

// Blocks until version > last_version; copies payload into out (cap
// out_cap). Returns new version (>0); -EAGAIN timeout; -EPIPE closed;
// -EMSGSIZE out buffer too small (required size in *out_len).
int64_t rtc_read(int h, uint64_t last_version, char* out, uint64_t out_cap,
                 uint64_t* out_len, int64_t timeout_ms) {
  ChannelHeader* hdr = g_handles[h].hdr;
  timespec ts;
  deadline_after_ms(&ts, timeout_ms);
  if (lock_robust(hdr) != 0) return -EINVAL;
  while (!hdr->closed && hdr->version <= last_version) {
    uint32_t seen = __atomic_load_n(&hdr->seq, __ATOMIC_SEQ_CST);
    pthread_mutex_unlock(&hdr->mu);
    if (wait_seq(hdr, seen, &ts) == ETIMEDOUT) return -EAGAIN;
    if (lock_robust(hdr) != 0) return -EINVAL;
  }
  if (hdr->closed && hdr->version <= last_version) {
    pthread_mutex_unlock(&hdr->mu);
    return -EPIPE;
  }
  *out_len = hdr->len;
  if (hdr->len > out_cap) {
    pthread_mutex_unlock(&hdr->mu);
    return -EMSGSIZE;
  }
  std::memcpy(out, arena(hdr), hdr->len);
  uint64_t v = hdr->version;
  hdr->acks += 1;
  wake_all(hdr);
  pthread_mutex_unlock(&hdr->mu);
  return static_cast<int64_t>(v);
}

uint64_t rtc_capacity(int h) { return g_handles[h].hdr->capacity; }

int rtc_close(int h) {
  ChannelHeader* hdr = g_handles[h].hdr;
  if (lock_robust(hdr) != 0) return -EINVAL;
  hdr->closed = 1;
  wake_all(hdr);
  pthread_mutex_unlock(&hdr->mu);
  return 0;
}

int rtc_unlink(const char* name) {
  return shm_unlink(name) == 0 ? 0 : -errno;
}

}  // extern "C"
