// Shared-memory object store — the plasma equivalent, redesigned.
//
// Reference: src/ray/object_manager/plasma/ (object_store.cc,
// plasma_allocator.cc, eviction_policy.cc): a store daemon owns an mmap
// arena and clients speak a unix-socket protocol to receive fds.
//
// TPU-era redesign: there is no store daemon and no socket protocol.
// One POSIX shm segment holds a fixed-layout header (robust process-shared
// mutex + open-addressing object table + free-span allocator state) and the
// data arena; every process on the node maps the same segment and operates
// on it directly under the robust lock. A crashed holder cannot wedge the
// store: robust-mutex EOWNERDEAD recovery marks the state consistent.
// Reads are zero-copy (Python maps the same pages; Get returns a pointer
// into this process's mapping, pinned by a refcount until Release).
//
// Eviction: LRU over sealed refcount-0 objects, triggered on allocation
// failure, exactly the role of plasma's eviction_policy.cc.
//
// Build: g++ -O2 -fPIC -shared -o libshm_store.so shm_store.cc -lpthread -lrt

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545053544f5246ULL;  // "RTPSTORF" (layout v2)
constexpr uint32_t kIdBytes = 32;
constexpr uint32_t kTableSize = 1 << 16;       // open addressing, power of 2
constexpr uint32_t kMaxFreeSpans = 8192;

struct Entry {
  uint8_t used;            // 0 empty, 1 live, 2 tombstone
  uint8_t sealed;
  uint8_t pending_delete;  // deleted while pinned: reap on last release
  uint8_t id_len;
  uint8_t id[kIdBytes];
  uint32_t refcount;
  uint64_t offset;
  uint64_t size;        // logical payload bytes (may be 0)
  uint64_t alloc;       // arena bytes actually reserved (>= 1)
  uint64_t lru_tick;
};

struct FreeSpan {
  uint64_t offset;
  uint64_t size;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;       // data arena bytes
  uint64_t used_bytes;
  uint64_t lru_clock;
  uint64_t num_objects;
  pthread_mutex_t lock;
  uint32_t num_free_spans;
  FreeSpan free_spans[kMaxFreeSpans];
  Entry table[kTableSize];
  // data arena follows, 64-byte aligned
};

constexpr uint64_t kDataOffset = (sizeof(Header) + 63) & ~uint64_t(63);

struct Store {
  Header* hdr;
  uint8_t* base;     // mapping base
  uint64_t map_size;
  // Per-process policy: when 0, a full arena fails the allocation with
  // -ENOSPC instead of silently dropping LRU objects — the caller then
  // SPILLS victims to disk first (object_store/shm.py spill-on-evict),
  // so primary copies are demoted, never lost.  Mirrors plasma's
  // spill-before-evict contract (reference plasma_store_runner +
  // local_object_manager.cc SpillObjects).
  int autoevict = 1;
};

constexpr int kMaxStores = 64;
Store g_stores[kMaxStores];
int g_num_stores = 0;

// A slot whose hdr is null was closed (rts_close) and may be reused by
// the next rts_create; every accessor must reject it.
bool ValidHandle(int h) {
  return h >= 0 && h < g_num_stores && g_stores[h].hdr != nullptr;
}

uint64_t HashId(const uint8_t* id, uint8_t len) {
  // FNV-1a
  uint64_t h = 1469598103934665603ULL;
  for (uint8_t i = 0; i < len; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

// Robust lock acquire: recover from a holder that died mid-critical-section.
int LockHeld(Header* hdr) {
  int rc = pthread_mutex_lock(&hdr->lock);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&hdr->lock);
    return 0;
  }
  return rc;
}

// Entries in pending_delete state are "zombies": logically deleted
// (invisible to get/contains/duplicate checks, their id immediately
// reusable by a fresh put) but their span stays allocated until the
// last pin releases. Probing continues past them, so chains stay valid.
Entry* FindEntry(Header* hdr, const uint8_t* id, uint8_t id_len) {
  uint64_t h = HashId(id, id_len);
  for (uint32_t probe = 0; probe < kTableSize; probe++) {
    Entry& e = hdr->table[(h + probe) & (kTableSize - 1)];
    if (e.used == 0) return nullptr;
    if (e.used == 1 && !e.pending_delete && e.id_len == id_len &&
        memcmp(e.id, id, id_len) == 0)
      return &e;
  }
  return nullptr;
}

Entry* FindSlot(Header* hdr, const uint8_t* id, uint8_t id_len) {
  uint64_t h = HashId(id, id_len);
  Entry* tomb = nullptr;
  for (uint32_t probe = 0; probe < kTableSize; probe++) {
    Entry& e = hdr->table[(h + probe) & (kTableSize - 1)];
    if (e.used == 0) return tomb ? tomb : &e;
    if (e.used == 2 && !tomb) tomb = &e;
    if (e.used == 1 && !e.pending_delete && e.id_len == id_len &&
        memcmp(e.id, id, id_len) == 0)
      return nullptr;  // exists
  }
  return tomb;
}

// ---- allocator: sorted free-span list, first fit, coalescing free ----

uint64_t AllocSpan(Header* hdr, uint64_t size) {
  for (uint32_t i = 0; i < hdr->num_free_spans; i++) {
    FreeSpan& s = hdr->free_spans[i];
    if (s.size >= size) {
      uint64_t off = s.offset;
      s.offset += size;
      s.size -= size;
      if (s.size == 0) {
        memmove(&hdr->free_spans[i], &hdr->free_spans[i + 1],
                (hdr->num_free_spans - i - 1) * sizeof(FreeSpan));
        hdr->num_free_spans--;
      }
      return off;
    }
  }
  return UINT64_MAX;
}

void FreeSpanInsert(Header* hdr, uint64_t offset, uint64_t size) {
  // insert sorted by offset, coalesce with neighbors
  uint32_t i = 0;
  while (i < hdr->num_free_spans && hdr->free_spans[i].offset < offset) i++;
  // coalesce left
  if (i > 0 && hdr->free_spans[i - 1].offset + hdr->free_spans[i - 1].size ==
                   offset) {
    hdr->free_spans[i - 1].size += size;
    // maybe also right
    if (i < hdr->num_free_spans &&
        hdr->free_spans[i - 1].offset + hdr->free_spans[i - 1].size ==
            hdr->free_spans[i].offset) {
      hdr->free_spans[i - 1].size += hdr->free_spans[i].size;
      memmove(&hdr->free_spans[i], &hdr->free_spans[i + 1],
              (hdr->num_free_spans - i - 1) * sizeof(FreeSpan));
      hdr->num_free_spans--;
    }
    return;
  }
  // coalesce right
  if (i < hdr->num_free_spans &&
      offset + size == hdr->free_spans[i].offset) {
    hdr->free_spans[i].offset = offset;
    hdr->free_spans[i].size += size;
    return;
  }
  if (hdr->num_free_spans >= kMaxFreeSpans) return;  // leak span (rare)
  memmove(&hdr->free_spans[i + 1], &hdr->free_spans[i],
          (hdr->num_free_spans - i) * sizeof(FreeSpan));
  hdr->free_spans[i] = {offset, size};
  hdr->num_free_spans++;
}

void DeleteEntryLocked(Header* hdr, Entry* e) {
  FreeSpanInsert(hdr, e->offset, e->alloc);
  hdr->used_bytes -= e->alloc;
  hdr->num_objects--;
  e->used = 2;  // tombstone keeps probe chains intact
  e->refcount = 0;
  e->sealed = 0;
  e->pending_delete = 0;
}

// Evict LRU sealed refcount-0 objects until at least `need` bytes could be
// allocated (best effort). Returns 1 if anything was evicted.
int EvictLocked(Header* hdr, uint64_t need) {
  int evicted_any = 0;
  while (true) {
    // would an allocation of `need` succeed now?
    for (uint32_t i = 0; i < hdr->num_free_spans; i++)
      if (hdr->free_spans[i].size >= need) return evicted_any;
    Entry* victim = nullptr;
    for (uint32_t i = 0; i < kTableSize; i++) {
      Entry& e = hdr->table[i];
      if (e.used == 1 && e.sealed && e.refcount == 0 &&
          (!victim || e.lru_tick < victim->lru_tick))
        victim = &e;
    }
    if (!victim) return evicted_any;
    DeleteEntryLocked(hdr, victim);
    evicted_any = 1;
  }
}

}  // namespace

extern "C" {

// Create (or open existing) store; returns handle >= 0, or -errno.
// Handle slots freed by rts_close are reused — long-lived processes
// that repeatedly open/close arenas (test harnesses, notebooks) must
// not exhaust the fixed table.
int rts_create(const char* name, uint64_t capacity) {
  int slot = -1;
  for (int i = 0; i < g_num_stores; i++) {
    if (g_stores[i].hdr == nullptr) {
      slot = i;
      break;
    }
  }
  if (slot < 0 && g_num_stores >= kMaxStores) return -ENOMEM;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0666);
  bool creator = fd >= 0;
  if (!creator) {
    if (errno != EEXIST) return -errno;
    fd = shm_open(name, O_RDWR, 0666);
    if (fd < 0) return -errno;
    // wait for creator to size + init it; bail if it never does
    // (creator crashed between shm_open and magic write)
    struct stat st;
    bool initialized = false;
    for (int spin = 0; spin < 10000; spin++) {
      if (fstat(fd, &st) == 0 && (uint64_t)st.st_size >= sizeof(Header)) {
        Header probe;
        if (pread(fd, &probe, sizeof(uint64_t), 0) == sizeof(uint64_t) &&
            probe.magic == kMagic) {
          initialized = true;
          break;
        }
      }
      usleep(1000);
    }
    if (!initialized) {
      close(fd);
      return -EAGAIN;
    }
  }
  uint64_t map_size = kDataOffset + capacity;
  if (creator && ftruncate(fd, map_size) != 0) {
    int err = errno;
    close(fd);
    shm_unlink(name);
    return -err;
  }
  if (!creator) {
    struct stat st;
    fstat(fd, &st);
    map_size = st.st_size;
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return -errno;
  Header* hdr = (Header*)mem;
  if (creator) {
    memset(hdr, 0, sizeof(Header));
    hdr->capacity = map_size - kDataOffset;
    hdr->num_free_spans = 1;
    hdr->free_spans[0] = {0, hdr->capacity};
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hdr->lock, &attr);
    pthread_mutexattr_destroy(&attr);
    __sync_synchronize();
    hdr->magic = kMagic;
  }
  int h = slot >= 0 ? slot : g_num_stores++;
  g_stores[h] = {hdr, (uint8_t*)mem + kDataOffset, map_size};
  return h;
}

int rts_open(const char* name) {
  // open-only: fail if the segment doesn't exist
  int fd = shm_open(name, O_RDWR, 0666);
  if (fd < 0) return -errno;
  close(fd);
  return rts_create(name, 0);
}

// Unmap this process's view of the store and free the handle slot for
// reuse. The shared segment itself (and other processes' mappings) are
// untouched — pair with rts_unlink to destroy the segment. Any pins
// this process still holds are abandoned; callers release them first.
int rts_close(int h) {
  if (!ValidHandle(h)) return -EINVAL;
  Store& st = g_stores[h];
  munmap((void*)st.hdr, st.map_size);
  st.hdr = nullptr;
  st.base = nullptr;
  st.map_size = 0;
  st.autoevict = 1;
  return 0;
}

// 0 ok; -EEXIST; -ENOSPC (even after eviction); -EINVAL.
int rts_put(int h, const uint8_t* id, uint32_t id_len,
            const uint8_t* data, uint64_t size) {
  if (!ValidHandle(h) || id_len > kIdBytes) return -EINVAL;
  Store& st = g_stores[h];
  Header* hdr = st.hdr;
  if (LockHeld(hdr) != 0) return -EINVAL;
  if (FindEntry(hdr, id, (uint8_t)id_len)) {
    pthread_mutex_unlock(&hdr->lock);
    return -EEXIST;
  }
  uint64_t sz = size ? size : 1;  // zero-size objects occupy 1 byte
  uint64_t off = AllocSpan(hdr, sz);
  if (off == UINT64_MAX && st.autoevict) {
    EvictLocked(hdr, sz);
    off = AllocSpan(hdr, sz);
  }
  if (off == UINT64_MAX) {
    pthread_mutex_unlock(&hdr->lock);
    return -ENOSPC;
  }
  Entry* e = FindSlot(hdr, id, (uint8_t)id_len);
  if (!e) {  // table full or duplicate
    FreeSpanInsert(hdr, off, sz);
    pthread_mutex_unlock(&hdr->lock);
    return -ENOSPC;
  }
  memcpy(st.base + off, data, size);
  e->used = 1;
  e->sealed = 1;
  e->pending_delete = 0;
  e->id_len = (uint8_t)id_len;
  memcpy(e->id, id, id_len);
  e->refcount = 0;
  e->offset = off;
  e->size = size;
  e->alloc = sz;
  e->lru_tick = ++hdr->lru_clock;
  hdr->used_bytes += sz;
  hdr->num_objects++;
  pthread_mutex_unlock(&hdr->lock);
  return 0;
}

// Two-phase create/seal (plasma CreateObject/Seal): the writer serializes
// DIRECTLY into the arena — no staging buffer, no extra memcpy. The entry
// is invisible to readers (and to eviction) until rts_seal; rts_abort
// frees the span of a failed write.
uint8_t* rts_create_unsealed(int h, const uint8_t* id, uint32_t id_len,
                             uint64_t size) {
  if (!ValidHandle(h) || id_len > kIdBytes) return nullptr;
  Store& st = g_stores[h];
  Header* hdr = st.hdr;
  if (LockHeld(hdr) != 0) return nullptr;
  if (FindEntry(hdr, id, (uint8_t)id_len)) {
    pthread_mutex_unlock(&hdr->lock);
    return nullptr;  // EEXIST
  }
  uint64_t sz = size ? size : 1;
  uint64_t off = AllocSpan(hdr, sz);
  if (off == UINT64_MAX && st.autoevict) {
    EvictLocked(hdr, sz);
    off = AllocSpan(hdr, sz);
  }
  if (off == UINT64_MAX) {
    pthread_mutex_unlock(&hdr->lock);
    return nullptr;  // ENOSPC
  }
  Entry* e = FindSlot(hdr, id, (uint8_t)id_len);
  if (!e) {
    FreeSpanInsert(hdr, off, sz);
    pthread_mutex_unlock(&hdr->lock);
    return nullptr;
  }
  e->used = 1;
  e->sealed = 0;  // invisible to rts_get and EvictLocked until sealed
  e->pending_delete = 0;
  e->id_len = (uint8_t)id_len;
  memcpy(e->id, id, id_len);
  e->refcount = 0;
  e->offset = off;
  e->size = size;
  e->alloc = sz;
  e->lru_tick = ++hdr->lru_clock;
  hdr->used_bytes += sz;
  hdr->num_objects++;
  uint8_t* ptr = st.base + off;
  pthread_mutex_unlock(&hdr->lock);
  return ptr;
}

int rts_seal(int h, const uint8_t* id, uint32_t id_len) {
  if (!ValidHandle(h)) return -EINVAL;
  Header* hdr = g_stores[h].hdr;
  if (LockHeld(hdr) != 0) return -EINVAL;
  Entry* e = FindEntry(hdr, id, (uint8_t)id_len);
  if (!e) {
    pthread_mutex_unlock(&hdr->lock);
    return -ENOENT;
  }
  e->sealed = 1;
  e->lru_tick = ++hdr->lru_clock;
  pthread_mutex_unlock(&hdr->lock);
  return 0;
}

int rts_abort(int h, const uint8_t* id, uint32_t id_len) {
  if (!ValidHandle(h)) return -EINVAL;
  Header* hdr = g_stores[h].hdr;
  if (LockHeld(hdr) != 0) return -EINVAL;
  Entry* e = FindEntry(hdr, id, (uint8_t)id_len);
  if (!e || e->sealed) {
    pthread_mutex_unlock(&hdr->lock);
    return -ENOENT;
  }
  DeleteEntryLocked(hdr, e);
  pthread_mutex_unlock(&hdr->lock);
  return 0;
}

// Returns pointer into this process's mapping (pinned), or NULL.
const uint8_t* rts_get(int h, const uint8_t* id, uint32_t id_len,
                       uint64_t* size_out) {
  if (!ValidHandle(h) || id_len > kIdBytes) return nullptr;
  Store& st = g_stores[h];
  Header* hdr = st.hdr;
  if (LockHeld(hdr) != 0) return nullptr;
  Entry* e = FindEntry(hdr, id, (uint8_t)id_len);
  if (!e || !e->sealed) {
    pthread_mutex_unlock(&hdr->lock);
    return nullptr;
  }
  e->refcount++;
  e->lru_tick = ++hdr->lru_clock;
  *size_out = e->size;
  const uint8_t* ptr = st.base + e->offset;
  pthread_mutex_unlock(&hdr->lock);
  return ptr;
}

int rts_release(int h, const uint8_t* id, uint32_t id_len) {
  if (!ValidHandle(h)) return -EINVAL;
  Header* hdr = g_stores[h].hdr;
  if (LockHeld(hdr) != 0) return -EINVAL;
  Entry* e = FindEntry(hdr, id, (uint8_t)id_len);
  if (!e || e->refcount == 0) {
    // The pin may belong to an entry deleted while pinned (now a
    // zombie that id lookups skip — possibly shadowed by a fresh live
    // entry under the same id). Zombies keep their id, so they sit on
    // the id's probe chain: walk it instead of scanning the table. If
    // the same id cycled through delete-while-pinned more than once
    // the counts alias across its zombies; each zombie is still reaped
    // exactly when its own count reaches zero.
    e = nullptr;
    uint64_t hh = HashId(id, (uint8_t)id_len);
    for (uint32_t probe = 0; probe < kTableSize; probe++) {
      Entry& z = hdr->table[(hh + probe) & (kTableSize - 1)];
      if (z.used == 0) break;
      if (z.used == 1 && z.pending_delete && z.refcount > 0 &&
          z.id_len == (uint8_t)id_len && memcmp(z.id, id, id_len) == 0) {
        e = &z;
        break;
      }
    }
  }
  if (e && e->refcount > 0) {
    e->refcount--;
    if (e->refcount == 0 && e->pending_delete) DeleteEntryLocked(hdr, e);
  }
  pthread_mutex_unlock(&hdr->lock);
  return e ? 0 : -ENOENT;
}

// Exact-pin release by (id, mapped address). The address disambiguates
// which generation of the id the pin belongs to when the object was
// deleted and re-put while the reader held its view; the id makes the
// lookup a hash-chain probe rather than a table scan.
int rts_release_addr(int h, const uint8_t* id, uint32_t id_len,
                     const uint8_t* ptr) {
  if (!ValidHandle(h) || id_len > kIdBytes) return -EINVAL;
  Store& st = g_stores[h];
  Header* hdr = st.hdr;
  if (ptr < st.base) return -EINVAL;
  uint64_t offset = (uint64_t)(ptr - st.base);
  if (LockHeld(hdr) != 0) return -EINVAL;
  uint64_t hh = HashId(id, (uint8_t)id_len);
  for (uint32_t probe = 0; probe < kTableSize; probe++) {
    Entry& e = hdr->table[(hh + probe) & (kTableSize - 1)];
    if (e.used == 0) break;
    if (e.used == 1 && e.offset == offset && e.refcount > 0 &&
        e.id_len == (uint8_t)id_len && memcmp(e.id, id, id_len) == 0) {
      e.refcount--;
      if (e.refcount == 0 && e.pending_delete) DeleteEntryLocked(hdr, &e);
      pthread_mutex_unlock(&hdr->lock);
      return 0;
    }
  }
  pthread_mutex_unlock(&hdr->lock);
  return -ENOENT;
}

int rts_contains(int h, const uint8_t* id, uint32_t id_len) {
  if (!ValidHandle(h)) return 0;
  Header* hdr = g_stores[h].hdr;
  if (LockHeld(hdr) != 0) return 0;
  int found = FindEntry(hdr, id, (uint8_t)id_len) != nullptr;
  pthread_mutex_unlock(&hdr->lock);
  return found;
}

int rts_delete(int h, const uint8_t* id, uint32_t id_len) {
  if (!ValidHandle(h)) return -EINVAL;
  Header* hdr = g_stores[h].hdr;
  if (LockHeld(hdr) != 0) return -EINVAL;
  Entry* e = FindEntry(hdr, id, (uint8_t)id_len);
  if (!e) {
    pthread_mutex_unlock(&hdr->lock);
    return -ENOENT;
  }
  if (e->refcount > 0) {
    // Pinned by a zero-copy reader: logically deleted now (invisible to
    // get/contains), pages reclaimed when the last pin releases.
    e->pending_delete = 1;
    pthread_mutex_unlock(&hdr->lock);
    return 0;
  }
  DeleteEntryLocked(hdr, e);
  pthread_mutex_unlock(&hdr->lock);
  return 0;
}

// Per-process: disable (0) / enable (1) silent LRU drop on full arena.
// With it disabled the caller runs the spill-before-evict loop (shm.py):
// rts_lru_candidate -> copy bytes to disk -> rts_delete -> retry.
int rts_set_autoevict(int h, int enabled) {
  if (!ValidHandle(h)) return -EINVAL;
  g_stores[h].autoevict = enabled ? 1 : 0;
  return 0;
}

// Id of the current LRU sealed refcount-0 object (the next eviction
// victim).  0 on success; -ENOENT when nothing is evictable.
int rts_lru_candidate(int h, uint8_t* out_id, uint32_t* out_id_len) {
  if (!ValidHandle(h)) return -EINVAL;
  Header* hdr = g_stores[h].hdr;
  if (LockHeld(hdr) != 0) return -EINVAL;
  Entry* victim = nullptr;
  for (uint32_t i = 0; i < kTableSize; i++) {
    Entry& e = hdr->table[i];
    if (e.used == 1 && e.sealed && !e.pending_delete && e.refcount == 0 &&
        (!victim || e.lru_tick < victim->lru_tick))
      victim = &e;
  }
  if (!victim) {
    pthread_mutex_unlock(&hdr->lock);
    return -ENOENT;
  }
  memcpy(out_id, victim->id, victim->id_len);
  *out_id_len = victim->id_len;
  pthread_mutex_unlock(&hdr->lock);
  return 0;
}

// Batched victim selection for the spill engine: up to `max_n` LRU
// sealed refcount-0 victims, oldest first, stopping early once their
// combined arena allocation reaches `need_bytes` (0 = no byte target,
// fill max_n).  One lock acquisition and one ctypes crossing replace a
// per-victim rts_lru_candidate loop — the demotion path's lock traffic
// under arena pressure was one acquisition per victim per failed put.
// out_ids is max_n * 32 bytes (kIdBytes per slot); out_id_lens is
// max_n u32s.  Returns the number of victims written (0 = nothing
// evictable), or -errno.
int rts_lru_candidates(int h, uint8_t* out_ids, uint32_t* out_id_lens,
                       uint32_t max_n, uint64_t need_bytes) {
  if (!ValidHandle(h) || max_n == 0) return -EINVAL;
  Header* hdr = g_stores[h].hdr;
  if (LockHeld(hdr) != 0) return -EINVAL;
  uint32_t n = 0;
  uint64_t gathered = 0;
  // selection sort over the (small) victim set: repeatedly take the
  // oldest not-yet-taken victim. max_n is small (spill batches), so the
  // quadratic scan stays cheap relative to the disk writes it feeds.
  uint64_t last_tick = 0;
  while (n < max_n && (need_bytes == 0 || gathered < need_bytes)) {
    Entry* victim = nullptr;
    for (uint32_t i = 0; i < kTableSize; i++) {
      Entry& e = hdr->table[i];
      if (e.used == 1 && e.sealed && !e.pending_delete && e.refcount == 0 &&
          (n == 0 || e.lru_tick > last_tick) &&
          (!victim || e.lru_tick < victim->lru_tick))
        victim = &e;
    }
    if (!victim) break;
    memcpy(out_ids + (uint64_t)n * kIdBytes, victim->id, victim->id_len);
    out_id_lens[n] = victim->id_len;
    last_tick = victim->lru_tick;
    gathered += victim->alloc;
    n++;
  }
  pthread_mutex_unlock(&hdr->lock);
  return (int)n;
}

int rts_stats(int h, uint64_t* capacity, uint64_t* used,
              uint64_t* num_objects) {
  if (!ValidHandle(h)) return -EINVAL;
  Header* hdr = g_stores[h].hdr;
  if (LockHeld(hdr) != 0) return -EINVAL;
  *capacity = hdr->capacity;
  *used = hdr->used_bytes;
  *num_objects = hdr->num_objects;
  pthread_mutex_unlock(&hdr->lock);
  return 0;
}

int rts_unlink(const char* name) {
  return shm_unlink(name) == 0 ? 0 : -errno;
}

}  // extern "C"
