"""Memory monitor + OOM worker-killing policy.

Reference: ``src/ray/common/memory_monitor.h`` (threshold check against
cgroup/system usage on a refresh interval) and
``src/ray/raylet/worker_killing_policy_group_by_owner.cc`` (victim
selection: prefer retriable work, then newest). The raylet kills a worker
BEFORE the kernel OOM-killer fires — a kernel OOM takes out an arbitrary
process (possibly the raylet itself); a policy kill converts it into one
retriable task failure with an attributable cause.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger(__name__)

_CGROUP_V1_LIMIT = "/sys/fs/cgroup/memory/memory.limit_in_bytes"
_CGROUP_V1_USAGE = "/sys/fs/cgroup/memory/memory.usage_in_bytes"
_CGROUP_V2_LIMIT = "/sys/fs/cgroup/memory.max"
_CGROUP_V2_USAGE = "/sys/fs/cgroup/memory.current"
# cgroup files report this when unconstrained
_UNLIMITED = 1 << 60


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            raw = f.read().strip()
        return None if raw == "max" else int(raw)
    except (OSError, ValueError):
        return None


def system_memory() -> Tuple[int, int]:
    """(used_bytes, total_bytes) — cgroup limits win over /proc/meminfo
    (inside a container the host total is a lie)."""
    for limit_path, usage_path in ((_CGROUP_V2_LIMIT, _CGROUP_V2_USAGE),
                                   (_CGROUP_V1_LIMIT, _CGROUP_V1_USAGE)):
        limit = _read_int(limit_path)
        usage = _read_int(usage_path)
        if limit is not None and usage is not None and limit < _UNLIMITED:
            return usage, limit
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
    except OSError:
        pass
    if total is None or avail is None:
        return 0, 1
    return total - avail, total


def process_rss(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError):
        pass
    return 0


class MemoryMonitor:
    """Polls usage; above the threshold, picks a victim worker.

    ``usage_fn`` is injectable for tests (default: real system memory).
    """

    def __init__(self, threshold: float,
                 usage_fn: Callable[[], Tuple[int, int]] = system_memory,
                 min_interval_s: float = 0.25):
        self.threshold = threshold
        self._usage_fn = usage_fn
        self._min_interval = min_interval_s
        self._last_check = 0.0
        self._last_result = (0, 1)

    def is_pressured(self) -> Tuple[bool, float]:
        now = time.monotonic()
        if now - self._last_check >= self._min_interval:
            self._last_check = now
            self._last_result = self._usage_fn()
        used, total = self._last_result
        frac = used / max(total, 1)
        return frac >= self.threshold, frac


def pick_victim(workers: List, rss_fn: Callable[[int], int] = process_rss):
    """Reference policy (worker_killing_policy_group_by_owner.cc): among
    killable workers, prefer (1) retriable leased tasks over actors,
    (2) the NEWEST work first (LIFO — it has lost the least progress),
    breaking ties by largest RSS so one kill actually relieves pressure."""
    candidates = []
    for w in workers:
        if w.state not in ("LEASED", "ACTOR") or w.pid is None:
            continue
        if not w.alive():
            continue
        retriable = w.state == "LEASED"  # tasks retry; actors restart at cost
        rss = rss_fn(w.pid)
        candidates.append((retriable, w.idle_since, rss, w))
    if not candidates:
        return None
    candidates.sort(key=lambda t: (not t[0], -t[1], -t[2]))
    return candidates[0][3]
