"""Forkserver-style worker factory.

Reference: the raylet's worker pool forks language workers on demand
(``src/ray/raylet/worker_pool.h``); CPython's cost there is dominated by
interpreter + import boot (~0.2-0.4 s per worker on this class of host,
measured in PERF_PLAN.md). The factory is a single warm Python process that
pre-imports the worker runtime and then ``os.fork()``s per request —
converting worker creation into a ~10 ms fork + registration handshake,
which is what the reference achieves with its prestarted worker cache.

Protocol (unix stream socket, length-prefixed pickle):
  request  {"env": {...}, "log_path": str, "cwd": str}
  reply    {"pid": int} | {"error": str}

The forked child closes the factory's sockets, replaces its environment,
redirects stdout/stderr into the per-worker session log, and runs the
normal ``worker_main.main()``. The factory reaps its children on a waitpid
thread so liveness probes (``os.kill(pid, 0)``) in the raylet never see
stale zombies. Runtime envs that swap the Python executable (pip/conda)
cannot ride a fork and keep the exec path in the raylet.

The factory must be started with the TPU preload DEFERRED (the raylet
passes the same stripped env it gives exec'd workers): a PJRT runtime
initialized before fork would hand every child broken device threads.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import sys
import threading


def _recv_msg(conn: socket.socket):
    head = b""
    while len(head) < 4:
        chunk = conn.recv(4 - len(head))
        if not chunk:
            return None
        head += chunk
    (n,) = struct.unpack("<I", head)
    body = b""
    while len(body) < n:
        chunk = conn.recv(n - len(body))
        if not chunk:
            return None
        body += chunk
    return pickle.loads(body)


def _send_msg(conn: socket.socket, obj) -> None:
    blob = pickle.dumps(obj)
    conn.sendall(struct.pack("<I", len(blob)) + blob)


def _reap_loop():
    while True:
        try:
            pid, _status = os.waitpid(-1, 0)
            if pid == 0:
                break
        except ChildProcessError:
            import time

            time.sleep(0.2)
        except OSError:
            return


def _child_main(req: dict, listener: socket.socket,
                conn: socket.socket) -> None:
    """Runs in the forked child: become a clean worker process."""
    import time as _time

    t_fork = _time.monotonic()
    listener.close()
    conn.close()
    os.setsid()  # own process group: raylet signals don't hit the factory
    log_fd = os.open(req["log_path"],
                     os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    os.dup2(log_fd, 1)
    os.dup2(log_fd, 2)
    os.close(log_fd)
    os.environ.clear()
    os.environ.update(req["env"])
    os.environ["RT_CHILD_T"] = repr(t_fork)  # worker_main logs the split
    if req.get("cwd"):
        os.chdir(req["cwd"])
    # flag values cached in the warm parent may disagree with this
    # worker's env (RT_* overrides arrive via req["env"])
    from ray_tpu.common.config import GLOBAL_CONFIG

    GLOBAL_CONFIG._cache.clear()

    import ray_tpu.core_worker.worker_main as wm

    try:
        wm.main()
    finally:
        os._exit(0)


def main(sock_path: str) -> None:
    # Pre-import everything the worker boot path needs: this is the whole
    # point — children inherit a warm interpreter.
    import asyncio  # noqa: F401
    import logging  # noqa: F401

    import cloudpickle  # noqa: F401
    import numpy  # noqa: F401

    import ray_tpu.core_worker.worker  # noqa: F401
    import ray_tpu.core_worker.worker_main  # noqa: F401
    import ray_tpu.rpc.rpc  # noqa: F401

    # Pre-dlopen the native extensions: children inherit the mappings,
    # cutting ~10-15 ms of per-worker boot (fastloop server + shm arena
    # open both dlopen these on first use). Load only — no sockets, no
    # arena handles, no threads from these libs cross the fork.
    from ray_tpu.rpc.native import load_fastloop, load_fastspec

    load_fastloop()
    load_fastspec()
    try:
        from ray_tpu.object_store import shm as _shm

        _shm._load()
    except Exception:  # noqa: BLE001 — workers just dlopen themselves
        pass

    threading.Thread(target=_reap_loop, daemon=True,
                     name="factory-reap").start()

    def _orphan_watch(parent=os.getppid()):
        # the factory is a direct child of the raylet: if the raylet is
        # SIGKILLed (multi-process-shape crash) nobody shuts the factory
        # down — reparenting is the death signal
        import time as _t

        while True:
            _t.sleep(2.0)
            if os.getppid() != parent:
                os._exit(0)

    threading.Thread(target=_orphan_watch, daemon=True,
                     name="factory-orphan-watch").start()
    if os.path.exists(sock_path):
        os.unlink(sock_path)
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(sock_path)
    listener.listen(64)
    import time as _time

    while True:
        try:
            conn, _ = listener.accept()
        except OSError:
            return
        try:
            t_acc = _time.monotonic()
            req = _recv_msg(conn)
            if req is None:
                continue
            if req.get("op") == "shutdown":
                _send_msg(conn, {"ok": True})
                return
            t_req = _time.monotonic()
            pid = os.fork()
            if pid == 0:
                _child_main(req, listener, conn)  # never returns
            if os.environ.get("RT_BOOT_TRACE"):
                print(f"factory: recv {1e3*(t_req-t_acc):.1f}ms fork "
                      f"{1e3*(_time.monotonic()-t_req):.1f}ms pid {pid}",
                      flush=True)
            _send_msg(conn, {"pid": pid})
        except Exception as e:  # noqa: BLE001 — keep serving
            try:
                _send_msg(conn, {"error": repr(e)})
            except OSError:
                pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


class MultiFactoryClient:
    """Round-robin over several forkserver processes. fork(2) copies the
    parent's page tables under mm-wide locks — ONE warm factory tops out
    at ~70-80 forks/s on this class of host, which caps sustained actor
    creation (every actor consumes a worker). K independent factories
    fork in parallel from separate address spaces."""

    def __init__(self, clients):
        self._clients = list(clients)
        self._i = 0
        self._lock = threading.Lock()

    def spawn(self, env: dict, log_path: str, cwd: str,
              timeout: float = 10.0) -> int:
        with self._lock:
            i = self._i
            self._i += 1
        last: Exception = RuntimeError("no factory processes")
        for k in range(len(self._clients)):
            c = self._clients[(i + k) % len(self._clients)]
            try:
                return c.spawn(env, log_path, cwd, timeout)
            except FactoryUnavailable as e:
                # connect-phase failure: this factory never saw the
                # request, safe to try the next one
                last = e
            # anything past connect (send/recv timeout etc.) may have
            # ALREADY forked the child — retrying on another factory
            # would double-spawn the same RT_WORKER_ID; propagate
        raise last

    def shutdown(self):
        for c in self._clients:
            c.shutdown()


class FactoryUnavailable(OSError):
    """The factory socket could not be reached (connect-phase failure):
    the request never arrived, so failing over to another factory cannot
    double-spawn."""


class FactoryClient:
    """Raylet-side handle: spawn workers through the factory socket."""

    def __init__(self, sock_path: str):
        self._path = sock_path

    def spawn(self, env: dict, log_path: str, cwd: str,
              timeout: float = 10.0) -> int:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(timeout)
        try:
            try:
                conn.connect(self._path)
            except OSError as e:
                raise FactoryUnavailable(str(e)) from e
            _send_msg(conn, {"env": env, "log_path": log_path, "cwd": cwd})
            reply = _recv_msg(conn)
        finally:
            conn.close()
        if reply is None or "pid" not in reply:
            raise RuntimeError(
                f"worker factory spawn failed: {reply!r}")
        return reply["pid"]

    def shutdown(self):
        try:
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.settimeout(2.0)
            conn.connect(self._path)
            _send_msg(conn, {"op": "shutdown"})
            conn.close()
        except OSError:
            pass


if __name__ == "__main__":
    main(sys.argv[1])
