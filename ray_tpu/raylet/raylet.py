"""Raylet — the per-node data-plane daemon.

Equivalent of the reference's raylet/NodeManager (src/ray/raylet/node_manager.cc,
raylet/main.cc): owns the worker pool, runs the local half of the two-level
lease scheduler (grant locally / spill to another node / queue), participates
in placement-group 2PC (prepare/commit/return of bundle resources,
raylet/placement_group_resource_manager.cc), reports resources to the GCS, and
detects worker death.

TPU specifics: leased TPU chips are exported to the worker via
``TPU_VISIBLE_CHIPS`` (mirroring the reference's accelerator plugin behavior,
python/ray/_private/accelerators/tpu.py:194-236) and node labels carry the
slice topology so gang policies can target one ICI domain.
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu.common.config import GLOBAL_CONFIG
from ray_tpu.common.ids import NodeID, PlacementGroupID, WorkerID
from ray_tpu.common.resources import (
    CPU,
    LABEL_NODE_ID,
    LABEL_SLICE_NAME,
    LABEL_SLICE_TOPOLOGY,
    NodeResources,
    ResourceRequest,
    TPU,
)
from ray_tpu.gcs.client import GcsClient
from ray_tpu.rpc.rpc import IoContext, RetryableRpcClient, RpcServer
from ray_tpu.scheduling import ClusterView, NodeEntry, policies

logger = logging.getLogger(__name__)


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    proc: Optional[subprocess.Popen]
    address: Optional[Tuple[str, int]] = None  # worker's RPC server
    fast_port: Optional[int] = None  # worker's fastloop dispatch port
    state: str = "STARTING"  # STARTING | IDLE | LEASED | ACTOR | DEAD
    env_key: Optional[str] = None  # runtime-env pool key (None = default env)
    lease_id: Optional[bytes] = None
    assignment: Optional[dict] = None  # unit-resource chip indices
    request: Optional[ResourceRequest] = None
    pg: Optional[Tuple[PlacementGroupID, int]] = None
    actor_id: Optional[bytes] = None
    job_id: Optional[bytes] = None  # job owning the current lease
    idle_since: float = field(default_factory=time.monotonic)
    registered: "asyncio.Event" = field(default_factory=asyncio.Event)
    # factory-forked workers have a bare pid instead of a Popen handle
    factory_pid: Optional[int] = None
    # cached raylet→worker RPC client (connect+HELLO once per worker, not
    # once per actor creation / device grant)
    rpc: Optional[RetryableRpcClient] = None

    def client(self) -> RetryableRpcClient:
        if self.rpc is None:
            self.rpc = RetryableRpcClient(self.address, deadline_s=30.0)
        return self.rpc

    def close_client(self) -> None:
        if self.rpc is not None:
            try:
                self.rpc.close()
            except Exception:  # noqa: BLE001
                pass
            self.rpc = None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else self.factory_pid

    def alive(self) -> bool:
        if self.proc is not None:
            return self.proc.poll() is None
        if self.factory_pid is None:
            return False
        try:
            os.kill(self.factory_pid, 0)  # zombies are reaped by the factory
            return True
        except OSError:
            return False

    def exit_reason(self) -> str:
        if self.proc is not None:
            return f"exit code {self.proc.returncode}"
        return "process gone"

    def _signal(self, sig) -> None:
        if self.proc is not None:
            (self.proc.terminate if sig == signal.SIGTERM
             else self.proc.kill)()
        elif self.factory_pid is not None:
            try:
                os.kill(self.factory_pid, sig)
            except OSError:
                pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def force_kill(self) -> None:
        self._signal(signal.SIGKILL)

    def wait_dead(self, timeout: float) -> None:
        if self.proc is not None:
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                pass
            return
        deadline = time.monotonic() + timeout
        while self.alive() and time.monotonic() < deadline:
            time.sleep(0.02)


@dataclass
class Bundle:
    request: ResourceRequest
    assignment: Optional[dict]  # chip indices reserved for the bundle
    committed: bool = False
    # lease accounting *within* the bundle
    available: ResourceRequest = None  # type: ignore[assignment]


class Raylet:
    def __init__(
        self,
        gcs_address: Tuple[str, int],
        host: str = "127.0.0.1",
        port: int = 0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        session_dir: Optional[str] = None,
        fake_worker_env: Optional[Dict[str, str]] = None,
    ):
        self.node_id = NodeID.from_random()
        self.gcs_address = tuple(gcs_address)
        self.server = RpcServer(host, port)
        self._io = IoContext.current()
        self.session_dir = session_dir or f"/tmp/rt/session_{os.getpid()}"
        os.makedirs(self.session_dir, exist_ok=True)

        resources = dict(resources or {})
        resources.setdefault(CPU, float(os.cpu_count() or 1))
        labels = dict(labels or {})
        labels[LABEL_NODE_ID] = self.node_id.hex()
        if GLOBAL_CONFIG.get("tpu_topology") and LABEL_SLICE_TOPOLOGY not in labels:
            labels[LABEL_SLICE_TOPOLOGY] = GLOBAL_CONFIG.get("tpu_topology")
        self.resources = NodeResources(resources, labels)

        self.view = ClusterView()  # replica of the cluster view
        self.gcs = GcsClient(self.gcs_address, client_id=f"raylet-{self.node_id.hex()[:8]}")
        self._workers: Dict[WorkerID, WorkerHandle] = {}
        # Worker IDs this raylet has seen die, kept (bounded) so the
        # liveness probe can distinguish "confirmed dead" from "never
        # hosted here" — owner-fetch fail-fast depends on that answer
        self._dead_workers: Dict[WorkerID, None] = {}
        self._leases: Dict[bytes, WorkerID] = {}
        self._bundles: Dict[PlacementGroupID, Dict[int, Bundle]] = {}
        self._pending_leases: List[dict] = []  # queued lease requests (waiters)
        self._drain_running = False  # single-flight pending-lease drain
        self._drain_again = False
        self._seq = 0
        self._stopped = False
        self._bg_tasks: List = []
        self._fake_worker_env = fake_worker_env or {}
        self._factory = None        # forkserver client (worker_factory.py)
        self._factory_procs: List[subprocess.Popen] = []
        self._refills_inflight = 0  # scheduled pool refills not yet STARTING
        from ray_tpu.runtime_env.agent import RuntimeEnvAgent

        self.runtime_env_agent = RuntimeEnvAgent(self.session_dir)
        from ray_tpu.raylet.memory_monitor import MemoryMonitor

        self.memory_monitor = MemoryMonitor(
            GLOBAL_CONFIG.get("memory_usage_threshold"),
            min_interval_s=GLOBAL_CONFIG.get(
                "memory_monitor_refresh_ms") / 1000.0)
        self._oom_kills = 0
        # warm-pool observability (util/metrics.py): pool depth + hit/miss
        # make actors_per_second regressions attributable — a collapsing
        # pool shows up as a miss streak, not just a slower bench row
        from ray_tpu.util import metrics as _metrics

        self._m_pool_size = _metrics.Gauge(
            "rt_worker_pool_size",
            "warm default-env workers (IDLE registered or STARTING)")
        self._m_pool_hits = _metrics.Counter(
            "rt_worker_pool_hits",
            "worker pops served by a warm pool worker (incl. adoptions)")
        self._m_pool_misses = _metrics.Counter(
            "rt_worker_pool_misses",
            "worker pops that had to fork (or wait for a fork)")
        self._m_pool_adoptions = _metrics.Counter(
            "rt_worker_pool_adoptions",
            "default-env pool workers reassigned to an env_vars/cwd-only "
            "runtime env via the configure_worker handshake")
        # node object transfer service (object_store/transfer.py): started
        # in start() so its port can ride the registration payload
        self._transfer = None
        self.cgroups = None
        if GLOBAL_CONFIG.get("cgroup_isolation_enabled"):
            from ray_tpu.raylet.cgroups import CgroupManager

            mgr = CgroupManager(self.node_id.hex())
            self.cgroups = mgr if mgr.enabled else None
        self._register_handlers()

    # ------------------------------------------------------------------ wiring
    def _register_handlers(self):
        s = self.server
        for name in (
            "health_check", "request_worker_lease", "request_worker_leases",
            "return_worker", "start_actor",
            "kill_worker", "worker_alive", "register_worker",
            "prepare_bundles", "commit_bundles",
            "return_bundles", "get_node_info", "debug_state", "notify_actor_dead",
        ):
            s.register(name, getattr(self, f"h_{name}"))

    def _registration_payload(self) -> dict:
        """What this node tells the GCS at (re-)registration: its shape plus
        everything it still hosts, so a restarted GCS can re-confirm replayed
        actor/PG records instead of failing them over (reference: raylet
        re-report on NotifyGCSRestart, node_manager.proto:397)."""
        live_actors = [
            {"actor_id": w.actor_id, "worker_id": w.worker_id.binary(),
             "address": w.address}
            for w in self._workers.values()
            if w.state == "ACTOR" and w.actor_id is not None
            and w.alive()
        ]
        held_bundles = [
            {"pg_id": pgid.binary(),
             "indices": [i for i, b in bundles.items() if b.committed]}
            for pgid, bundles in self._bundles.items()
        ]
        payload = dict(
            node_id=self.node_id.binary(),
            address=self.server.address,
            resources=self.resources.total.to_dict(),
            labels=self.resources.labels,
            live_actors=live_actors,
            held_bundles=held_bundles,
        )
        if self._transfer is not None:
            payload["transfer_address"] = list(self._transfer.address)
        return payload

    def start(self):
        self.server.start()
        if GLOBAL_CONFIG.get("transfer_service") and \
                GLOBAL_CONFIG.get("shm_store_enabled"):
            from ray_tpu.object_store.transfer import TransferServer

            self._transfer = TransferServer(self.node_id,
                                            host=self.server.address[0])
            self._transfer.start()
        reply = self.gcs.call("register_node", **self._registration_payload())
        GLOBAL_CONFIG.initialize(reply.get("system_config") or "{}")
        GLOBAL_CONFIG.reset_cache()
        # seed the local cluster view, then keep it fresh via pubsub
        for info in self.gcs.get_all_nodes():
            if info["alive"]:
                snap = info["resources"]
                entry = NodeEntry(
                    node_id=NodeID(info["node_id"]),
                    address=tuple(info["address"]),
                    resources=NodeResources.from_snapshot(snap),
                )
                self.view.upsert(entry)
        self.gcs.subscriber.subscribe("resources", self._on_resources_update)
        self.gcs.subscriber.subscribe("node", self._on_node_update)
        self.gcs.subscriber.subscribe("system_config", self._on_system_config)
        self.gcs.subscriber.subscribe("job", self._on_job_update)
        self._io.spawn_threadsafe(self._report_loop())
        self._io.spawn_threadsafe(self._reap_loop())
        if GLOBAL_CONFIG.get("worker_factory_enabled"):
            self._start_factory()
        n_prestart = GLOBAL_CONFIG.get("num_prestart_workers")
        if n_prestart > 0:
            # warm pool: actor/task creation becomes a registration
            # handshake instead of an interpreter boot (reference:
            # worker_pool prestart)
            async def prestart():
                for _ in range(n_prestart):
                    try:
                        await self._start_worker()
                    except Exception:  # noqa: BLE001 — warm pool is optional
                        logger.debug("prestart failed", exc_info=True)
                        return

            self._io.spawn_threadsafe(prestart())
        logger.info("raylet %s serving at %s", self.node_id.hex()[:8], self.server.address)

    def _replenish_pool(self):
        """Keep ``num_prestart_workers`` warm default-env workers forked in
        the BACKGROUND: sustained actor churn then pipelines interpreter
        forks behind control-plane work instead of paying them on every
        creation's critical path (reference: worker_pool.cc
        PrestartWorkers on demand-prediction).

        Replenishment is CONCURRENT up to the node-wide fork cap: a burst
        of creations larger than the pool used to serialize behind one
        fork per consumed worker (the round-5 cold-start hole) — now the
        whole deficit forks at once and the pool refills in one fork
        latency instead of ``deficit`` of them."""
        target = GLOBAL_CONFIG.get("num_prestart_workers")
        if target <= 0 or self._stopped:
            return
        if self._factory is None:
            # no warm forkserver attached (yet): a proactive refill would
            # exec-spawn a full interpreter (~1.5 s CPU) per consumed
            # worker — short-lived clusters (tests) must not pay that;
            # demand-driven pops still spawn as before
            return
        warm = sum(1 for w in self._workers.values()
                   if w.env_key is None
                   and (w.state == "STARTING"  # pid may not be known yet
                        or (w.state == "IDLE" and w.alive())))
        self._m_pool_size.set(warm)
        # refills already scheduled but not yet visible as STARTING
        # handles (the factory spawn hasn't returned a pid yet) count
        # toward the deficit, or a pop burst schedules the whole deficit
        # once per pop and overshoots the watermark
        inflight = getattr(self, "_refills_inflight", 0)
        deficit = target - warm - inflight
        if deficit <= 0:
            return
        starting = sum(1 for w in self._workers.values()
                       if w.state == "STARTING")
        slots = max(0, GLOBAL_CONFIG.get("maximum_startup_concurrency")
                    - starting - inflight)
        n = min(deficit, slots)
        if n <= 0:
            return
        self._refills_inflight = inflight + n

        async def refill():
            try:
                await self._start_worker()
            except Exception:  # noqa: BLE001 — warm pool is best-effort
                logger.debug("pool replenish failed", exc_info=True)
            finally:
                self._refills_inflight -= 1

        for _ in range(n):
            self._io.spawn_threadsafe(refill())

    def _start_factory(self):
        """Boot the forkserver worker factories (worker_factory.py): warm
        interpreters whose forks cut worker creation from interpreter-boot
        cost to ~fork cost. ``worker_factory_procs`` of them run side by
        side — fork(2) serializes inside one address space (~12 ms per
        fork of a warm interpreter here), so parallel factories are what
        raise the sustained worker-supply ceiling that actor churn rides."""
        from ray_tpu.common.tpu_detect import defer_tpu_preload
        from ray_tpu.raylet.worker_factory import (FactoryClient,
                                                   MultiFactoryClient)

        n = max(1, GLOBAL_CONFIG.get("worker_factory_procs"))
        env = defer_tpu_preload(dict(os.environ))
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        if pkg_root not in env.get("PYTHONPATH", "").split(os.pathsep):
            env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                                 if env.get("PYTHONPATH") else pkg_root)
        log_path = os.path.join(self.session_dir, "worker_factory.log")
        socks = []
        self._factory_procs = []
        for i in range(n):
            sock = os.path.join(
                self.session_dir,
                f"factory_{self.node_id.hex()[:8]}_{i}.sock")
            socks.append(sock)
            self._factory_procs.append(subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.raylet.worker_factory",
                 sock],
                env=env, stdout=open(log_path, "ab"),
                stderr=subprocess.STDOUT))

        def wait_ready(procs=list(self._factory_procs)):
            # Non-blocking adoption: raylet startup (and anything timing
            # it, e.g. the autoscaler's launch bookkeeping) must not stall
            # on interpreter boot; workers exec-spawn until the factory
            # sockets are up, then forks take over. Factories that come
            # up are adopted incrementally.
            deadline = time.monotonic() + 30.0
            ready: list = []
            waiting = list(zip(procs, socks))
            while waiting and time.monotonic() < deadline \
                    and not self._stopped:
                still = []
                for proc, sock in waiting:
                    if os.path.exists(sock):
                        ready.append(FactoryClient(sock))
                        if self._factory_procs and not self._stopped:
                            self._factory = MultiFactoryClient(ready)
                    elif proc.poll() is None:
                        still.append((proc, sock))
                waiting = still
                if waiting:
                    time.sleep(0.05)
            if not ready:
                logger.warning("worker factory failed to start; "
                               "exec spawning stays in effect")
            else:
                logger.debug("%d worker factories up", len(ready))

        import threading as _threading

        _threading.Thread(target=wait_ready, daemon=True,
                          name="factory-wait").start()

    def stop(self):
        self._stopped = True
        if self._transfer is not None:
            self._transfer.stop()
            self._transfer = None
        store = getattr(self, "_shm_stats_store", None)
        if store is not None:
            self._shm_stats_store = None
            try:
                store.close()  # free the fixed-size per-process handle slot
            except Exception:  # noqa: BLE001
                pass
        for t in self._bg_tasks:
            t.cancel()
        for w in list(self._workers.values()):
            if w.alive():
                w.terminate()
        for w in list(self._workers.values()):
            w.wait_dead(3.0)
            if w.alive():
                w.force_kill()
        if getattr(self, "_factory", None) is not None:
            self._factory.shutdown()
            self._factory = None
        for proc in getattr(self, "_factory_procs", []):
            proc.terminate()
            try:
                proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                proc.kill()
        self._factory_procs = []
        self.gcs.close()
        self.server.stop()
        if self.cgroups is not None:
            self.cgroups.cleanup()
        # reclaim this node's shm object-store segment (every raylet owns
        # its node's segment — not just the head; tmpfs leaks are RAM leaks)
        try:
            from ray_tpu.object_store.shm import node_shm_name
            from ray_tpu.object_store.shm import unlink as shm_unlink

            shm_unlink(node_shm_name(self.node_id))
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------- cluster view sync
    def _on_resources_update(self, node_hex: str, msg: dict):
        nid = NodeID.from_hex(node_hex)
        if nid == self.node_id:
            return
        entry = self.view.get(nid)
        if entry is None:
            return
        self.view.update_resources(nid, msg["snapshot"], msg["seq"])
        self._io.loop.call_soon_threadsafe(self._try_grant_pending)

    def _on_system_config(self, key: str, msg: dict):
        try:
            GLOBAL_CONFIG.set_system_config_value(key, msg.get("value"))
        except ValueError:
            logger.warning("unknown system_config key from GCS: %s", key)

    def _on_job_update(self, job_hex: str, msg: dict):
        """A finished job's leased workers must be reclaimed: the driver
        died or exited, nobody will return those leases, and the held CPUs
        would starve the cluster (reference: the raylet kills a dead job's
        workers — worker_pool.cc HandleJobFinished)."""
        if (msg or {}).get("state") != "FINISHED":
            return

        async def reclaim():
            try:
                job_raw = bytes.fromhex(job_hex)
            except ValueError:
                return
            for w in list(self._workers.values()):
                if (w.job_id == job_raw and w.lease_id is not None
                        and w.state != "DEAD"):
                    logger.info("reclaiming worker %s leased by finished "
                                "job %s", w.worker_id.hex()[:8], job_hex[:8])
                    # account first (frees lease, reports actor death),
                    # then terminate the process
                    await self._on_worker_dead(w, "job finished")
                    self._kill_worker_proc(w)
            # queued lease requests from the dead job will never be
            # collected either — fail them out of the queue
            for item in self._pending_leases:
                if item.get("job_id") == job_raw and not item["future"].done():
                    item["future"].set_result({"status": "job_finished"})

        self._io.spawn_threadsafe(reclaim())

    def _on_node_update(self, node_hex: str, msg: dict):
        nid = NodeID.from_hex(node_hex)
        if msg.get("state") == "DEAD":
            self.view.mark_dead(nid)
        elif msg.get("state") == "ALIVE" and nid != self.node_id:
            entry = self.view.get(nid)
            if entry is None:
                # fetch details lazily on next report; register placeholder
                self.view.upsert(
                    NodeEntry(node_id=nid, address=tuple(msg["address"]),
                              resources=NodeResources({}))
                )

    def _system_stats(self) -> dict:
        """Per-node system stats shipped with every resource report —
        the dashboard's node view + per-node Prometheus gauges come from
        here (reference: per-node reporter agents,
        ``dashboard/modules/reporter/reporter_agent.py``)."""
        import os as _os

        from ray_tpu.raylet.memory_monitor import system_memory

        used, total = system_memory()
        try:
            load1 = _os.getloadavg()[0]
        except OSError:
            load1 = 0.0
        out = {
            "mem_used_bytes": used,
            "mem_total_bytes": total,
            "cpu_load_1m": load1,
            "num_workers": len(self._workers),
            "num_pending_leases": len(self._pending_leases),
        }
        # native shm object-store occupancy (rts_stats) — the node-local
        # plasma equivalent's capacity/used/object-count. Handle opened
        # once and cached (the report loop runs every 100ms).
        try:
            store = getattr(self, "_shm_stats_store", None)
            if store is None:
                from ray_tpu.object_store.shm import (ShmObjectStore,
                                                      node_shm_name)

                store = ShmObjectStore(
                    node_shm_name(self.node_id), create=False)
                self._shm_stats_store = store
            cap, used_b, n_obj = store.stats()
            out["object_store_capacity_bytes"] = cap
            out["object_store_used_bytes"] = used_b
            out["object_store_num_objects"] = n_obj
        except Exception:  # noqa: BLE001 — store may be disabled
            pass
        return out

    async def _report_loop(self):
        period = GLOBAL_CONFIG.get("raylet_report_resources_period_ms") / 1000.0
        while not self._stopped:
            self._seq += 1
            try:
                # stats come from /proc + shm reads — OFF the loop: under
                # fork churn those reads take tens of ms in the kernel,
                # and on the loop they were ~45% of sampled loop time
                # (stalling every lease grant and worker registration)
                stats = await asyncio.to_thread(self._system_stats)
                # fencing relay: once this raylet has followed a promoted
                # leader, its reports carry that epoch so a stale primary
                # deposes itself (gcs/failover.py).  The kwarg is omitted
                # entirely until then — a pre-fencing GCS would reject the
                # unknown keyword (its handler signature predates it).
                fencing = ({"leader_epoch": self.gcs.leader_epoch_seen}
                           if self.gcs.leader_epoch_seen else {})
                reply = await self.gcs.call_async(
                    "report_resources",
                    node_id=self.node_id.binary(),
                    snapshot=self.resources.snapshot(),
                    seq=self._seq,
                    **fencing,
                    # queued lease demands feed the autoscaler's bin-packing
                    # (reference: SchedulerResourceReporter → autoscaler
                    # state, gcs_autoscaler_state_manager)
                    pending=[item["request"].to_dict()
                             for item in self._pending_leases
                             if not item["future"].done()],
                    stats=stats,
                )
                if isinstance(reply, dict) and reply.get("unknown"):
                    # GCS restarted and lost us: re-register with live state
                    await self.gcs.call_async(
                        "register_node", **self._registration_payload())
            except Exception:  # noqa: BLE001 - GCS may be restarting
                pass
            # keep our own entry in the local view fresh for spillback scoring
            self.view.upsert(
                NodeEntry(
                    node_id=self.node_id,
                    address=self.server.address,
                    resources=self.resources,
                    seq=self._seq,
                )
            )
            await asyncio.sleep(period)

    async def _reap_loop(self):
        """Detect dead worker processes; free leases; reap idle workers;
        relieve memory pressure (reference memory_monitor.h loop)."""
        idle_ttl = GLOBAL_CONFIG.get("idle_worker_killing_time_threshold_ms") / 1000.0
        while not self._stopped:
            for w in list(self._workers.values()):
                if w.state != "DEAD" and (w.pid is not None) \
                        and not w.alive():
                    await self._on_worker_dead(w, w.exit_reason())
            if GLOBAL_CONFIG.get("memory_monitor_enabled"):
                # /proc reads off-loop (same reason as _report_loop)
                pressured, frac = await asyncio.to_thread(
                    self.memory_monitor.is_pressured)
                if pressured:
                    await self._relieve_memory_pressure(frac)
            # reap long-idle workers beyond a small cache
            idle = [w for w in self._workers.values() if w.state == "IDLE"]
            keep = max(2, GLOBAL_CONFIG.get("num_prestart_workers"))
            if len(idle) > keep:
                idle.sort(key=lambda w: w.idle_since)
                now = time.monotonic()
                for w in idle[: len(idle) - keep]:
                    if now - w.idle_since > idle_ttl:
                        self._kill_worker_proc(w)
            await asyncio.sleep(0.2)

    async def _relieve_memory_pressure(self, frac: float):
        """Kill one policy-chosen worker per check (reference
        worker_killing_policy): retriable leased tasks first, newest
        first — converting an imminent kernel OOM into one attributable,
        retriable failure."""
        from ray_tpu.raylet.memory_monitor import pick_victim

        victim = pick_victim(list(self._workers.values()))
        if victim is None:
            return
        self._oom_kills += 1
        logger.warning(
            "memory pressure %.1f%% >= %.1f%%: killing worker %s (%s) "
            "per OOM policy", frac * 100,
            self.memory_monitor.threshold * 100,
            victim.worker_id.hex()[:8], victim.state)
        # kill FIRST, account after: freeing the lease before the hog is
        # dead would re-grant pending work while pressure is still rising,
        # and the cgroup can only be removed once its member is gone
        if victim.alive():
            victim.force_kill()
            import asyncio as _asyncio

            await _asyncio.to_thread(victim.wait_dead, 5.0)
        await self._on_worker_dead(
            victim,
            f"killed by the memory monitor: node memory usage "
            f"{frac:.0%} >= threshold "
            f"{self.memory_monitor.threshold:.0%}")

    @staticmethod
    def _wait_proc(proc, timeout: float):
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            pass

    def _record_worker_dead(self, worker_id: WorkerID):
        self._dead_workers[worker_id] = None
        while len(self._dead_workers) > 4096:
            self._dead_workers.pop(next(iter(self._dead_workers)))

    async def _on_worker_dead(self, w: WorkerHandle, reason: str):
        if w.state == "DEAD":
            return
        self._record_worker_dead(w.worker_id)
        prev_state = w.state
        w.state = "DEAD"
        w.close_client()
        logger.warning("worker %s dead (%s): %s", w.worker_id.hex()[:8], prev_state, reason)
        if w.lease_id is not None:
            self._free_lease(w)
        if prev_state == "ACTOR":
            self._free_worker_resources(w)
            if w.actor_id is not None:
                try:
                    await self.gcs.call_async(
                        "report_actor_state", actor_id=w.actor_id, state="DEAD",
                        worker_id=w.worker_id.binary(),
                        death_cause=f"worker died: {reason}",
                    )
                except Exception:  # noqa: BLE001
                    pass
        self._workers.pop(w.worker_id, None)
        self.runtime_env_agent.release(w.env_key)
        if self.cgroups is not None:
            self.cgroups.remove_worker_cgroup(w.worker_id.hex())
        self._try_grant_pending()
        # a dead worker may have been the pool's warm capacity (actor
        # churn kills one worker per actor): refill in the background
        self._replenish_pool()

    def _kill_worker_proc(self, w: WorkerHandle):
        self._record_worker_dead(w.worker_id)
        if w.state != "DEAD":
            self.runtime_env_agent.release(w.env_key)
            # killing a live worker MUST return its held resources: this
            # pops the worker from the table, so the reap loop will never
            # run _on_worker_dead for it — without this, every kill of a
            # leased/actor worker (job reclaim, kill_worker RPC, OOM
            # killer) permanently leaks its CPUs/chips
            if w.lease_id is not None:
                self._free_lease(w)
            else:
                self._free_worker_resources(w)
        w.state = "DEAD"
        w.close_client()
        self._workers.pop(w.worker_id, None)
        if w.alive():
            w.terminate()
        self._try_grant_pending()

    # ------------------------------------------------------------ worker pool
    async def _start_worker(self, ctx=None) -> WorkerHandle:
        from ray_tpu.runtime_env.agent import WorkerEnvContext

        ctx = ctx or WorkerEnvContext()
        worker_id = WorkerID.from_random()
        from ray_tpu.common.tpu_detect import defer_tpu_preload

        # Defer the TPU runtime preload: the sitecustomize jax/PJRT boot
        # costs ~1.9 s per process and only TPU-holding workers need it. The
        # stashed vars are restored (and the PJRT plugin registered) by
        # h_set_visible_devices when a TPU lease lands on the worker.
        env = defer_tpu_preload(dict(os.environ))
        env.update(self._fake_worker_env)
        env = ctx.apply(env)
        # the framework itself must stay importable when a runtime env
        # changes cwd (it may only be reachable via the driver's cwd today)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        if pkg_root not in env.get("PYTHONPATH", "").split(os.pathsep):
            env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                                 if env.get("PYTHONPATH") else pkg_root)
        env["RT_WORKER_ID"] = worker_id.hex()
        # spawn timestamp (CLOCK_MONOTONIC is machine-wide): worker_main
        # logs fork→entry latency against it — the part of the supply
        # path that lives outside the worker's own boot trace
        env["RT_SPAWN_T"] = repr(time.monotonic())
        env["RT_RAYLET_ADDR"] = f"{self.server.address[0]}:{self.server.address[1]}"
        env["RT_GCS_ADDR"] = f"{self.gcs_address[0]}:{self.gcs_address[1]}"
        env["RT_NODE_ID"] = self.node_id.hex()
        env["RT_SESSION_DIR"] = self.session_dir
        log_path = os.path.join(self.session_dir, f"worker-{worker_id.hex()[:8]}.log")
        # Default-env workers fork off the warm factory (~10 ms); runtime
        # envs that may swap the interpreter (pip/conda) keep the exec path.
        if self._factory is not None and ctx.env_key is None:
            try:
                pid = await asyncio.to_thread(
                    self._factory.spawn, env, log_path,
                    ctx.cwd or os.getcwd())
                w = WorkerHandle(worker_id=worker_id, proc=None,
                                 factory_pid=pid, env_key=ctx.env_key)
                self.runtime_env_agent.acquire(ctx.env_key)
                if self.cgroups is not None:
                    cg = self.cgroups.create_worker_cgroup(worker_id.hex())
                    if cg is not None:
                        self.cgroups.attach(cg, pid)
                self._workers[worker_id] = w
                logger.debug("factory-forked worker %s (pid %s)",
                             worker_id.hex()[:8], pid)
                return w
            except Exception:  # noqa: BLE001 — fall back to exec spawn
                logger.warning("factory spawn failed; exec fallback",
                               exc_info=True)
        def _exec_spawn():
            # open+fork+exec off-loop: the exec fallback runs whenever no
            # factory is attached (pip/conda envs, early boot) and a fork
            # stalls the IO loop ~10ms (PERF_PLAN round-8 boot trace)
            logfile = open(log_path, "ab")
            try:
                return subprocess.Popen(
                    [sys.executable, "-m",
                     "ray_tpu.core_worker.worker_main"],
                    env=env, stdout=logfile, stderr=subprocess.STDOUT,
                    cwd=ctx.cwd or os.getcwd(),
                )
            finally:
                # the child inherited the fd; the parent copy only leaks
                logfile.close()

        proc = await asyncio.to_thread(_exec_spawn)
        w = WorkerHandle(worker_id=worker_id, proc=proc, env_key=ctx.env_key)
        self.runtime_env_agent.acquire(ctx.env_key)
        if self.cgroups is not None:
            cg = self.cgroups.create_worker_cgroup(worker_id.hex())
            if cg is not None:
                self.cgroups.attach(cg, proc.pid)
        self._workers[worker_id] = w
        logger.debug("forked worker %s (pid %s)", worker_id.hex()[:8], proc.pid)
        return w

    async def h_register_worker(self, worker_id: bytes, address,
                                fast_port: Optional[int] = None):
        w = self._workers.get(WorkerID(worker_id))
        if w is None:
            # worker from a previous life / unknown: tell it to exit
            return {"ok": False}
        w.address = tuple(address)
        w.fast_port = fast_port
        if w.state == "STARTING":
            w.state = "IDLE"
            w.idle_since = time.monotonic()
        w.registered.set()
        logger.debug("worker %s registered at %s", WorkerID(worker_id).hex()[:8], address)
        self._try_grant_pending()
        return {"ok": True}

    async def _pop_worker(self, timeout: float = None, ctx=None) -> Optional[WorkerHandle]:
        """Get an idle registered worker IN THE SAME runtime env (pools are
        keyed by env hash, reference: worker_pool.h), forking if needed.
        ``maximum_startup_concurrency`` caps forks NODE-WIDE, across envs.

        Envs that differ from the default only by env_vars/cwd ADOPT a
        warm default-env worker via the configure_worker handshake
        instead of forking; envs needing fork-time state (staged
        PYTHONPATH trees: pip/py_modules/working_dir) are ineligible and
        keep the fork path."""
        timeout = timeout or GLOBAL_CONFIG.get("worker_register_timeout_s")
        env_key = ctx.env_key if ctx is not None else None
        deadline = time.monotonic() + timeout
        missed = False
        while True:
            for w in self._workers.values():
                if (w.state == "IDLE" and w.env_key == env_key
                        and w.alive()):
                    w.state = "LEASED"
                    if not missed:
                        self._m_pool_hits.inc()
                    if env_key is None:
                        # consumed a warm default-env worker: refill in
                        # the background so the next pop finds one too
                        self._replenish_pool()
                    return w
            if env_key is not None and ctx is not None \
                    and self._adoptable(ctx):
                w = await self._adopt_pool_worker(ctx)
                if w is not None:
                    if not missed:
                        self._m_pool_hits.inc()
                    return w
            if not missed:
                missed = True
                self._m_pool_misses.inc()
            starting_all = [w for w in self._workers.values()
                            if w.state == "STARTING"]
            if len(starting_all) < GLOBAL_CONFIG.get("maximum_startup_concurrency"):
                w = await self._start_worker(ctx)
            else:
                starting_same = [w for w in starting_all if w.env_key == env_key]
                # at the fork cap: wait for ANY starting worker to register
                # (freeing a fork slot), then re-check
                w = (starting_same or starting_all)[0]
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                logger.warning("pop_worker: registration timeout")
                return None
            logger.debug("pop_worker: waiting registration of %s",
                         w.worker_id.hex()[:8])
            try:
                await asyncio.wait_for(w.registered.wait(),
                                       min(remaining, 5.0))
            except asyncio.TimeoutError:
                if time.monotonic() >= deadline:
                    logger.warning("pop_worker: registration timeout for %s",
                                   w.worker_id.hex()[:8])
                    return None
                continue
            if w.env_key == env_key and w.state == "IDLE":
                w.state = "LEASED"
                return w
            # someone else took it, it's a different env, or it died — retry

    # env_vars that only take effect at interpreter boot/import time:
    # applying them post-adoption would silently do nothing (fork applies
    # them pre-exec), so envs carrying any of these must really fork.
    _BOOT_ENV_KEYS = frozenset({
        "PYTHONPATH", "PYTHONHOME", "PYTHONSTARTUP", "LD_PRELOAD",
        "LD_LIBRARY_PATH", "JAX_PLATFORMS", "XLA_FLAGS", "TPU_VISIBLE_CHIPS",
    })

    def _adoptable(self, ctx) -> bool:
        """True when a warm default-env worker can be reassigned to this
        env with post-boot fixups only: no staged PYTHONPATH trees and no
        boot-time env_vars (RT_* flags may be read once at worker boot,
        so they need a fork too)."""
        if ctx.pythonpath_prepend:
            return False
        return not any(k in self._BOOT_ENV_KEYS or k.startswith("RT_")
                       for k in ctx.env_vars)

    async def _adopt_pool_worker(self, ctx) -> Optional[WorkerHandle]:
        """Reassign a warm default-env worker to an env_vars/cwd-only
        runtime env: one configure_worker RPC instead of a fork. The
        worker keeps its new env_key for the rest of its life (its
        process env HAS been mutated), so later pops pool it under that
        env. A half-configured worker (RPC failed) is killed, never
        reused."""
        for w in list(self._workers.values()):
            if not (w.state == "IDLE" and w.env_key is None
                    and w.address is not None and w.alive()):
                continue
            w.state = "LEASED"  # claim before awaiting
            try:
                await w.client().call_async("configure_worker",
                                            env_vars=ctx.env_vars,
                                            cwd=ctx.cwd, timeout=10.0)
            except Exception:  # noqa: BLE001 — env state unknown: discard
                logger.warning("pool-worker adoption failed; forking",
                               exc_info=True)
                self._kill_worker_proc(w)
                return None
            w.env_key = ctx.env_key
            self.runtime_env_agent.acquire(ctx.env_key)
            self._m_pool_adoptions.inc()
            self._replenish_pool()  # consumed a default-env warm worker
            logger.debug("adopted pool worker %s into env %s",
                         w.worker_id.hex()[:8], ctx.env_key[:8])
            return w
        return None

    # ------------------------------------------------------------- scheduling
    def _local_available(self, request: ResourceRequest,
                         pg: Optional[Tuple[PlacementGroupID, int]]) -> bool:
        if pg is not None:
            pg_id, idx = pg
            bundle = self._bundles.get(pg_id, {}).get(idx)
            return bundle is not None and bundle.committed and \
                request.resources.is_subset_of(bundle.available.resources)
        return self.resources.is_available(request)

    def _allocate_local(self, request: ResourceRequest,
                        pg: Optional[Tuple[PlacementGroupID, int]]):
        """Returns an assignment or None. Availability is RE-CHECKED here:
        callers may have awaited (env staging) since their _local_available
        check, and a competing grant can win the resources meanwhile."""
        if pg is not None:
            pg_id, idx = pg
            bundle = self._bundles.get(pg_id, {}).get(idx)
            if bundle is None or not bundle.committed or \
                    not request.resources.is_subset_of(
                        bundle.available.resources):
                return None
            bundle.available = ResourceRequest(
                (bundle.available.resources - request.resources).to_dict()
            )
            # chips come from the bundle's reservation
            return {k: list(v) for k, v in (bundle.assignment or {}).items()}
        return self.resources.allocate(request)

    async def h_request_worker_lease(self, lease_id: bytes, resources: dict,
                                     strategy=None, pg: Optional[tuple] = None,
                                     grant_only_local: bool = False,
                                     runtime_env: Optional[dict] = None,
                                     job_id: Optional[bytes] = None,
                                     locality: Optional[dict] = None):
        """Two-level scheduling (reference: node_manager.proto:413 +
        cluster_task_manager.h): grant locally, spill, or queue."""
        request = ResourceRequest.from_dict(resources) if isinstance(resources, dict) and "resources" in resources else ResourceRequest(resources)
        pg_key = (PlacementGroupID(pg[0]), pg[1]) if pg else None
        logger.debug("lease request %s res=%s", lease_id[:4].hex(), request.resources.to_dict())

        # Argument-locality: when the hinted best node is NOT this one and
        # could run the task, route there before burning a local grant —
        # a local grant means the args pay the wire (submitter.py sends
        # the owner-built {node_hex: arg_bytes} hint).
        if locality and GLOBAL_CONFIG.get("locality_scheduling") \
                and pg_key is None and not grant_only_local:
            strategy_obj = (pickle.loads(strategy)
                            if isinstance(strategy, bytes) else None)
            node = policies.pick_node(self.view, request, strategy_obj,
                                      local_node=self.node_id,
                                      arg_bytes_by_node=locality)
            if node is not None and node.node_id != self.node_id:
                return {"status": "spill", "node_id": node.node_id.binary(),
                        "address": node.address}
        if self._local_available(request, pg_key):
            granted = await self._grant_lease(lease_id, request, pg_key,
                                              runtime_env, job_id=job_id)
            if granted is not None:
                return granted
        if pg_key is not None or grant_only_local:
            # PG leases are node-pinned; queue locally until bundle frees
            # up.  "pin" marks explicitly local-only requests (e.g. the
            # submitter's final spill hop) so the drain never re-spills
            # them — bouncing a hop-budget-exhausted lease defeats the pin.
            fut = asyncio.get_running_loop().create_future()
            self._pending_leases.append(
                {"lease_id": lease_id, "request": request, "pg": pg_key,
                 "runtime_env": runtime_env, "future": fut, "job_id": job_id,
                 "pin": grant_only_local}
            )
            return await fut
        # consider spilling to another node
        strategy_obj = pickle.loads(strategy) if isinstance(strategy, bytes) else None
        node = policies.pick_node(self.view, request, strategy_obj,
                                  local_node=self.node_id,
                                  arg_bytes_by_node=locality)
        if node is not None and node.node_id != self.node_id:
            return {"status": "spill", "node_id": node.node_id.binary(),
                    "address": node.address}
        feasible_somewhere = any(
            e.resources.is_feasible(request) for e in self.view.alive_nodes()
        )
        if not feasible_somewhere and not GLOBAL_CONFIG.get(
                "autoscaling_enabled"):
            return {"status": "infeasible"}
        # With autoscaling, an infeasible-now demand stays queued: its
        # pending entry is what the autoscaler bin-packs a new node for.
        fut = asyncio.get_running_loop().create_future()
        self._pending_leases.append(
            {"lease_id": lease_id, "request": request, "pg": None,
             "runtime_env": runtime_env, "future": fut, "job_id": job_id,
             "locality": locality}
        )
        return await fut

    async def h_request_worker_leases(self, lease_ids: List[bytes],
                                      resources: dict,
                                      runtime_env: Optional[dict] = None,
                                      job_id: Optional[bytes] = None):
        """Coalesced lease grants: grant as many same-shape leases as are
        IMMEDIATELY satisfiable locally, in one RPC (the submitter asks
        for min(queue depth, batch size) at once instead of one round
        trip per lease).  Never blocks and never spills — anything not
        granted here falls back to the single-lease protocol, which owns
        queueing/spill/infeasible semantics.

        Fairness cap: one coalesced request takes at most HALF of what
        currently fits (never less than one).  Under contention several
        clients fan out simultaneously; first-come winner-takes-all
        grants plus lease retention would hand one client the whole node
        for its queue's lifetime and serialize the rest (measured: the
        multi-client row collapsed 4x without this cap), while geometric
        halving leaves every simultaneous claimant a share."""
        request = (ResourceRequest.from_dict(resources)
                   if isinstance(resources, dict) and "resources" in resources
                   else ResourceRequest(resources))
        fits = self._count_fits(request)
        cap = max(1, fits // 2)

        async def one(lid: bytes):
            # concurrent pops: each grant's worker fork/claim overlaps the
            # others', exactly as N single-lease handlers would — a serial
            # loop here measured 1.5x the ramp latency
            if not self._local_available(request, None):
                return None
            g = await self._grant_lease(lid, request, None, runtime_env,
                                        job_id=job_id)
            if g is None or g.get("status") != "granted":
                return None
            g["lease_id"] = lid
            return g

        # return_exceptions: one failed grant must not discard siblings
        # that ALREADY leased workers — dropping their grants would leak
        # the leases (resources deducted, no holder to return them)
        results = await asyncio.gather(*(one(lid)
                                         for lid in lease_ids[:cap]),
                                       return_exceptions=True)
        granted = []
        for r in results:
            if isinstance(r, BaseException):
                logger.warning("coalesced grant failed: %s", r)
            elif r is not None:
                granted.append(r)
        return {"granted": granted}

    def _count_fits(self, request: ResourceRequest) -> int:
        """How many copies of ``request`` the node's free resources hold
        right now (0 if it doesn't fit at all)."""
        avail = self.resources.snapshot().get("available", {})
        fits = None
        for name, qty in request.resources.to_dict().items():
            if qty <= 0:
                continue
            n = int(float(avail.get(name, 0.0)) // qty)
            fits = n if fits is None else min(fits, n)
        if fits is None:  # zero-resource request: bounded by nothing
            return 1 if self._local_available(request, None) else 0
        return fits

    async def _materialize_env(self, runtime_env: Optional[dict]):
        """Stage the env off-loop (file copies must not stall the raylet)."""
        if not runtime_env:
            from ray_tpu.runtime_env.agent import WorkerEnvContext

            return WorkerEnvContext()
        return await asyncio.to_thread(
            self.runtime_env_agent.get_or_create, runtime_env)

    async def _grant_lease(self, lease_id: bytes, request: ResourceRequest,
                           pg_key, runtime_env=None,
                           job_id: Optional[bytes] = None) -> Optional[dict]:
        # Materialize the env only on the node that will actually grant —
        # a request that spills elsewhere must not stage files here.
        try:
            ctx = await self._materialize_env(runtime_env)
        except Exception as e:  # noqa: BLE001 - RuntimeEnvError + staging IO
            return {"status": "env_error", "error": str(e)}
        assignment = self._allocate_local(request, pg_key)
        if assignment is None:
            return None
        w = await self._pop_worker(ctx=ctx)
        if w is None:
            # couldn't start a worker: roll back
            if pg_key is None:
                self.resources.free(request, assignment)
            else:
                self._return_to_bundle(pg_key, request)
            return None
        w.lease_id = lease_id
        w.request = request
        w.assignment = assignment
        w.pg = pg_key
        w.job_id = job_id
        self._leases[lease_id] = w.worker_id
        # tell the worker its chip visibility before it runs anything
        tpu_chips = (assignment or {}).get(TPU)
        if w.address is not None and tpu_chips is not None:
            try:
                # bounded: a wedged worker must not stall the lease grant
                # for the cached client's full 30s retry window
                await w.client().call_async("set_visible_devices",
                                            tpu_chips=tpu_chips,
                                            timeout=5.0)
            except Exception:  # noqa: BLE001
                pass
        return {
            "status": "granted",
            "worker_id": w.worker_id.binary(),
            "worker_address": w.address,
            # the worker's native dispatch port: the lease holder opens
            # its fast task channel against it (submitter.py)
            "worker_fast_port": w.fast_port,
            "node_id": self.node_id.binary(),
        }

    def _free_worker_resources(self, w: WorkerHandle):
        """Return a worker's held resources to the right pool: its PG bundle
        if it was leased inside one, the node pool otherwise."""
        if w.request is None:
            w.pg = None
            return
        if w.pg is not None:
            self._return_to_bundle(w.pg, w.request)
        else:
            self.resources.free(w.request, w.assignment)
        w.request = None
        w.assignment = None
        w.pg = None

    def _return_to_bundle(self, pg_key, request: ResourceRequest):
        pg_id, idx = pg_key
        bundles = self._bundles.get(pg_id)
        if bundles and idx in bundles:
            b = bundles[idx]
            b.available = ResourceRequest(
                (b.available.resources + request.resources).to_dict()
            )

    def _free_lease(self, w: WorkerHandle):
        if w.lease_id is None:
            return
        self._leases.pop(w.lease_id, None)
        w.lease_id = None
        w.job_id = None
        self._free_worker_resources(w)

    async def h_return_worker(self, lease_id: bytes, disconnect: bool = False):
        wid = self._leases.get(lease_id)
        if wid is None:
            return False
        w = self._workers.get(wid)
        if w is None:
            return False
        self._free_lease(w)
        if disconnect or not w.alive():
            self._kill_worker_proc(w)
        else:
            w.state = "IDLE"
            w.idle_since = time.monotonic()
        self._try_grant_pending()
        return True

    def _try_grant_pending(self):
        if not self._pending_leases:
            return
        # Single-flight: concurrent drain() tasks interleaving at the
        # grant await both leaked leases and dropped queue items when each
        # rebuilt _pending_leases (round-5 review findings).  One drain
        # runs at a time; triggers during a run coalesce into one rerun.
        if self._drain_running:
            self._drain_again = True
            return
        self._drain_running = True

        async def drain():
            try:
                while True:
                    self._drain_again = False
                    await self._drain_pending_leases_once()
                    if not self._drain_again:
                        return
            finally:
                self._drain_running = False

        self._io.spawn_threadsafe(drain())

    async def _drain_pending_leases_once(self):
        still: List[dict] = []
        for item in self._pending_leases:
            if item["future"].done():
                continue
            if self._local_available(item["request"], item["pg"]):
                granted = await self._grant_lease(
                    item["lease_id"], item["request"], item["pg"],
                    item.get("runtime_env"), job_id=item.get("job_id"))
                if granted is not None:
                    if not item["future"].done():
                        item["future"].set_result(granted)
                    else:
                        # the job-finished reclaim resolved the future
                        # while we granted: give the lease back or it
                        # (and its worker) leaks forever
                        await self.h_return_worker(item["lease_id"])
                    continue
            if item["pg"] is None and not item.get("pin"):
                # re-evaluate spilling: a REMOTE node may have freed up
                # while we were queued (its gossip triggers this drain)
                node = policies.pick_node(
                    self.view, item["request"], None, local_node=self.node_id,
                    arg_bytes_by_node=item.get("locality"))
                if node is not None and node.node_id != self.node_id \
                        and not item["future"].done():
                    item["future"].set_result(
                        {"status": "spill", "node_id": node.node_id.binary(),
                         "address": node.address})
                    continue
            still.append(item)
        self._pending_leases[:] = still

    # ---------------------------------------------------------------- actors
    async def h_start_actor(self, creation_spec: bytes):
        spec = pickle.loads(creation_spec)
        request = spec.required_resources
        pg_key = None
        from ray_tpu.common.task_spec import PlacementGroupStrategy

        if isinstance(spec.scheduling_strategy, PlacementGroupStrategy):
            pg_key = (spec.scheduling_strategy.placement_group_id,
                      spec.scheduling_strategy.bundle_index)
        if not self._local_available(request, pg_key):
            return {"ok": False, "reason": "resources unavailable"}
        try:
            ctx = await self._materialize_env(spec.runtime_env)
        except Exception as e:  # noqa: BLE001
            # env failures are fatal for the actor, not retryable placement
            return {"ok": False, "fatal": True,
                    "reason": f"runtime env setup failed: {e}"}
        assignment = self._allocate_local(request, pg_key)
        if assignment is None:
            # a competing grant won the resources during env staging
            return {"ok": False, "reason": "resources unavailable"}
        w = await self._pop_worker(ctx=ctx)
        if w is None:
            if pg_key is None:
                self.resources.free(request, assignment)
            else:
                self._return_to_bundle(pg_key, request)
            return {"ok": False, "reason": "no worker"}
        w.state = "ACTOR"
        w.pg = pg_key
        w.request = request
        w.assignment = assignment
        w.actor_id = spec.actor_id.binary()
        # the actor consumed a warm worker for good (actor workers die with
        # their actor — state isolation, as in the reference); refill the
        # pool off the critical path so the NEXT creation finds one warm
        self._replenish_pool()
        tpu_chips = (assignment or {}).get(TPU)
        try:
            c = w.client()
            # device grant rides the creation push: ONE worker RPC on the
            # creation critical path instead of set_visible_devices +
            # create_actor round-tripping serially
            await c.call_async("create_actor", creation_spec=creation_spec,
                               node_id=self.node_id.binary(),
                               tpu_chips=tpu_chips, timeout=120.0)
        except Exception as e:  # noqa: BLE001
            logger.warning("create_actor push failed: %s", e)
            await self._on_worker_dead(w, f"create_actor failed: {e}")
            return {"ok": False, "reason": str(e)}
        return {"ok": True, "worker_id": w.worker_id.binary(), "worker_address": w.address}

    async def h_kill_worker(self, worker_id: bytes):
        w = self._workers.get(WorkerID(worker_id))
        if w is None:
            return False
        self._kill_worker_proc(w)
        return True

    async def h_worker_alive(self, worker_id: bytes):
        """Three-valued liveness probe for object-owner fail-fast
        (core_worker fetch): ``known`` is False for a worker this raylet
        never hosted (foreign node, driver) — the caller must keep its
        patient retry path for those."""
        wid = WorkerID(worker_id)
        w = self._workers.get(wid)
        if w is not None:
            return {"known": True, "alive": w.state != "DEAD"}
        return {"known": wid in self._dead_workers, "alive": False}

    async def h_notify_actor_dead(self, worker_id: bytes):
        """Worker-side graceful actor exit (e.g. __rt_terminate__)."""
        w = self._workers.get(WorkerID(worker_id))
        if w is not None:
            await self._on_worker_dead(w, "actor exited")
        return True

    # --------------------------------------------------------------- PG (2PC)
    async def h_prepare_bundles(self, pg_id: bytes, bundles: Dict[int, dict]):
        pgid = PlacementGroupID(pg_id)
        # Idempotent re-prepare (GCS may 2PC the same pg_id again after a
        # restart/reschedule): free any allocation this node still holds for
        # an index being re-prepared, or it leaks when overwritten below.
        existing = self._bundles.get(pgid, {})
        for idx in list(bundles):
            old = existing.pop(int(idx), None) or existing.pop(idx, None)
            if old is not None:
                self.resources.free(old.request, old.assignment)
        prepared: Dict[int, Bundle] = {}
        for idx, bdict in bundles.items():
            request = ResourceRequest.from_dict(bdict)
            assignment = self.resources.allocate(request)
            if assignment is None:
                # roll back everything prepared in this call
                for b in prepared.values():
                    self.resources.free(b.request, b.assignment)
                return False
            prepared[idx] = Bundle(request=request, assignment=assignment,
                                   available=ResourceRequest(request.resources.to_dict()))
        self._bundles.setdefault(pgid, {}).update(prepared)
        return True

    async def h_commit_bundles(self, pg_id: bytes):
        for b in self._bundles.get(PlacementGroupID(pg_id), {}).values():
            b.committed = True
        self._try_grant_pending()
        return True

    async def h_return_bundles(self, pg_id: bytes):
        bundles = self._bundles.pop(PlacementGroupID(pg_id), {})
        for b in bundles.values():
            self.resources.free(b.request, b.assignment)
        # kill workers still leased inside the PG
        for w in list(self._workers.values()):
            if w.pg is not None and w.pg[0] == PlacementGroupID(pg_id):
                self._kill_worker_proc(w)
        self._try_grant_pending()
        return True

    # ------------------------------------------------------------------ misc
    async def h_health_check(self):
        return True

    async def h_get_node_info(self):
        return {
            "node_id": self.node_id.binary(),
            "address": self.server.address,
            "resources": self.resources.snapshot(),
            "num_workers": len(self._workers),
            "session_dir": self.session_dir,
        }

    def _spill_state(self) -> dict:
        """Node spill-subsystem snapshot: this handle's engine counters
        plus the shared spill dir's on-disk footprint.  Disk scan —
        callers must run it OFF the loop (h_debug_state to_threads it)."""
        out: dict = {}
        try:
            store = getattr(self, "_shm_stats_store", None)
            if store is None:
                return out
            out["engine"] = store.spill_stats()
            spill_dir = store._spill_dir
            if spill_dir and os.path.isdir(spill_dir):
                files = bytes_on_disk = 0
                with os.scandir(spill_dir) as it:
                    for e in it:
                        if e.name.startswith("."):
                            continue
                        try:
                            bytes_on_disk += e.stat().st_size
                            files += 1
                        except OSError:
                            continue
                out["dir"] = {"path": spill_dir, "files": files,
                              "bytes": bytes_on_disk}
        except Exception:  # noqa: BLE001 — diagnostics are best-effort
            pass
        return out

    async def h_debug_state(self):
        def _spill():
            return self._spill_state()

        spill = await asyncio.to_thread(_spill)
        return {
            "spill": spill,
            "workers": {
                w.worker_id.hex()[:8]: {"state": w.state, "addr": w.address}
                for w in self._workers.values()
            },
            "pending_leases": len(self._pending_leases),
            "bundles": {
                pid.hex()[:8]: {i: b.committed for i, b in bs.items()}
                for pid, bs in self._bundles.items()
            },
            "resources": self.resources.snapshot(),
            "oom_kills": self._oom_kills,
            "io_stats": dict(self._io.stats),
            "worker_pool": {
                "warm": sum(1 for w in self._workers.values()
                            if w.env_key is None and w.state in
                            ("IDLE", "STARTING")),
                "hits": sum(self._m_pool_hits.snapshot()
                            ["values"].values()),
                "misses": sum(self._m_pool_misses.snapshot()
                              ["values"].values()),
                "adoptions": sum(self._m_pool_adoptions.snapshot()
                                 ["values"].values()),
            },
        }


def main():
    import argparse
    import faulthandler
    import threading

    logging.basicConfig(level=logging.INFO)
    # SIGUSR1 → all-thread stack dump (the `ray stack` equivalent the
    # worker entrypoint already has; a congested raylet loop is diagnosed
    # by sampling this under load)
    faulthandler.register(signal.SIGUSR1, all_threads=True)
    p = argparse.ArgumentParser()
    p.add_argument("--gcs", required=True, help="host:port of the GCS")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--resources", default="{}", help="JSON resource dict")
    p.add_argument("--labels", default="{}", help="JSON label dict")
    p.add_argument("--session-dir", default=None,
                   help="shared session directory (worker logs, runtime "
                   "envs); the multi-process launcher passes the driver's")
    args = p.parse_args()
    import json

    host, _, port = args.gcs.partition(":")
    raylet = Raylet(
        (host, int(port)), args.host, args.port,
        resources=json.loads(args.resources), labels=json.loads(args.labels),
        session_dir=args.session_dir,
    )
    raylet.start()
    # node_id and session_dir ride the READY line: the multi-process
    # launcher needs them for the driver's CoreWorker + shm teardown
    print(f"RAYLET_READY {raylet.server.address[0]}:"
          f"{raylet.server.address[1]} {raylet.node_id.hex()} "
          f"{raylet.session_dir}", flush=True)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    done.wait()
    # clean stop kills workers/factories — a SIGTERM'd raylet must not
    # orphan its children (the supervisor tears the node down through here)
    raylet.stop()


if __name__ == "__main__":
    main()
