"""Per-worker cgroup resource isolation.

Reference: ``src/ray/common/cgroup2/`` (cgroup manager placing worker
processes into a node-scoped cgroup subtree with memory limits, so a
runaway worker is contained by the kernel instead of taking down the
raylet). Enabled via config flag ``cgroup_isolation_enabled``; degrades to
a no-op when the cgroup filesystem isn't writable (non-root, or cgroup
delegation not granted) — the memory monitor remains the fallback line of
defense either way.

Supports cgroup v1 (memory controller dir) and v2 (unified hierarchy).
"""

from __future__ import annotations

import logging
import os
import shutil
from typing import Optional

logger = logging.getLogger(__name__)

_V1_ROOT = "/sys/fs/cgroup/memory"
_V2_ROOT = "/sys/fs/cgroup"


class CgroupManager:
    def __init__(self, node_id_hex: str):
        self._base: Optional[str] = None
        self._v2 = False
        base_name = f"rt_{node_id_hex[:12]}"
        if os.path.isdir(_V1_ROOT):
            base = os.path.join(_V1_ROOT, base_name)
        elif os.path.exists(os.path.join(_V2_ROOT, "cgroup.controllers")):
            base = os.path.join(_V2_ROOT, base_name)
            self._v2 = True
        else:
            logger.info("no cgroup hierarchy found; isolation disabled")
            return
        try:
            os.makedirs(base, exist_ok=True)
            self._base = base
        except OSError as e:
            logger.info("cgroup fs not writable (%s); isolation disabled", e)

    @property
    def enabled(self) -> bool:
        return self._base is not None

    def create_worker_cgroup(self, worker_id_hex: str,
                             memory_bytes: Optional[int] = None) -> Optional[str]:
        """Returns the cgroup dir, or None when disabled/failed."""
        if self._base is None:
            return None
        path = os.path.join(self._base, f"w_{worker_id_hex[:12]}")
        try:
            os.makedirs(path, exist_ok=True)
            if memory_bytes:
                limit_file = "memory.max" if self._v2 \
                    else "memory.limit_in_bytes"
                with open(os.path.join(path, limit_file), "w") as f:
                    f.write(str(int(memory_bytes)))
            return path
        except OSError as e:
            logger.warning("worker cgroup setup failed: %s", e)
            return None

    @staticmethod
    def attach(path: str, pid: int) -> bool:
        try:
            with open(os.path.join(path, "cgroup.procs"), "w") as f:
                f.write(str(pid))
            return True
        except OSError as e:
            logger.warning("cgroup attach of pid %s failed: %s", pid, e)
            return False

    def remove_worker_cgroup(self, worker_id_hex: str) -> None:
        if self._base is None:
            return
        path = os.path.join(self._base, f"w_{worker_id_hex[:12]}")
        try:  # a cgroup dir with dead members removes with rmdir
            os.rmdir(path)
        except OSError:
            pass

    def cleanup(self) -> None:
        if self._base is None:
            return
        for name in os.listdir(self._base):
            try:
                os.rmdir(os.path.join(self._base, name))
            except OSError:
                pass
        try:
            os.rmdir(self._base)
        except OSError:
            pass
        self._base = None
