"""Small shared containers."""

from __future__ import annotations

from collections import OrderedDict


class BoundedSet:
    """Insertion-ordered set with FIFO eviction past a capacity bound.

    Used for per-process bookkeeping keyed by task/object ids (cancelled
    ids, pending cancel requests): correctness needs recent entries, and a
    hard cap keeps day-scale drivers from growing without bound."""

    def __init__(self, cap: int = 16384):
        self._d: OrderedDict = OrderedDict()
        self._cap = cap

    def add(self, key) -> None:
        self._d[key] = None
        self._d.move_to_end(key)
        while len(self._d) > self._cap:
            self._d.popitem(last=False)

    def discard(self, key) -> None:
        self._d.pop(key, None)

    def __contains__(self, key) -> bool:
        return key in self._d

    def __len__(self) -> int:
        return len(self._d)
