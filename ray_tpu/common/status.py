"""Framework exceptions (reference: src/ray/common/status.h + python/ray/exceptions.py)."""

from __future__ import annotations


class RtError(Exception):
    """Base class for all framework errors."""


class RtTimeoutError(RtError, TimeoutError):
    pass


class RtConnectionError(RtError, ConnectionError):
    pass


class RtSystemError(RtError):
    """Internal invariant violation."""


class GcsDeposedError(RtError):
    """This GCS lost leadership (a standby promoted with a higher epoch).
    Clients treat it as "not the leader" and rotate; see gcs/failover.py
    for the fencing protocol."""

    def __init__(self, epoch: int, new_epoch: int):
        self.epoch = epoch
        self.new_epoch = new_epoch
        super().__init__(
            f"GCS deposed: this leader's epoch {epoch} was superseded by "
            f"epoch {new_epoch}")

    def __reduce__(self):  # two-arg __init__: default reduce would break
        return (GcsDeposedError, (self.epoch, self.new_epoch))


class ControlPlaneDiedError(RtError):
    """A dedicated control-plane process (GCS server or raylet) died while
    the cluster was in use (multi-process deployment shape,
    ``control_plane_procs``).  Raised by new control-plane operations —
    task submission, actor creation — after the supervisor detects the
    death; already-dispatched work on live workers is unaffected."""

    def __init__(self, component: str, detail: str = ""):
        self.component = component
        self.detail = detail
        super().__init__(
            f"control-plane process {component!r} died"
            + (f": {detail}" if detail else ""))

    def __reduce__(self):  # two-arg __init__: default reduce would break
        return (ControlPlaneDiedError, (self.component, self.detail))


class TaskError(RtError):
    """A task raised an exception; re-raised at `get` on the caller."""

    def __init__(self, task_id=None, cause: BaseException | None = None, traceback_str: str = ""):
        self.task_id = task_id
        self.cause = cause
        self.traceback_str = traceback_str
        super().__init__(f"task {task_id} failed: {cause!r}\n{traceback_str}")


class TaskCancelledError(RtError):
    """The task was cancelled via cancel(); raised at `get` on its refs
    (reference: python/ray/exceptions.py TaskCancelledError)."""

    def __init__(self, message: str = "the task was cancelled"):
        super().__init__(message)


class WorkerCrashedError(RtError):
    """The worker executing the task died unexpectedly."""


class ActorDiedError(RtError):
    def __init__(self, actor_id=None, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"actor {actor_id} died: {reason}")


class ActorUnavailableError(RtError):
    """Actor temporarily unreachable (restarting); call may be retried."""


class ObjectLostError(RtError):
    def __init__(self, object_id=None, reason: str = ""):
        self.object_id = object_id
        self.reason = reason
        super().__init__(f"object {object_id} lost: {reason}")

    def __reduce__(self):  # default reduce would re-wrap the message as
        # the object_id on every pickle hop, nesting "object object ..."
        return (ObjectLostError, (self.object_id, self.reason))


class SpillFailedError(RtError):
    """A spill write to external storage failed (disk full, unwritable
    dir, dead mount) — the primary copy could NOT be demoted to disk.

    Deliberately NOT an OSError subclass: the spill paths' historical
    ``except OSError`` guards (arena-full retries, best-effort cleanup)
    must not swallow it.  Raised by the shm spill engine at the next
    spill operation after a writer-thread failure, and synchronously by
    ``put_or_spill``/the demotion loop when the write is refused up
    front; ``CoreWorker._pack_result`` lets it surface as a task error
    instead of silently dropping the node-durability guarantee."""


class ObjectStoreFullError(RtError):
    pass


class PlacementGroupError(RtError):
    pass


class RuntimeEnvSetupError(RtError):
    pass


class PendingCallsLimitExceeded(RtError):
    pass
