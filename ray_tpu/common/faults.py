"""Deterministic fault injection for cross-process boundaries.

Every place a message leaves (or enters) a process on the get/put/lease
path declares a *named fault point*::

    from ray_tpu.common import faults
    ...
    faults.fault_point("transfer.pull.recv")

When no fault is armed — the production state — ``fault_point`` is a
single module-level flag check and an immediate return: no dict lookup,
no lock, no allocation.  When a schedule is armed for the name, the call
raises :class:`FaultInjected` (a ``ConnectionError``) according to the
schedule, so the failure flows through exactly the code path a real
transport failure would take.

Schedules are deterministic so a chaos test can aim at one specific
edge ("the SECOND recv of this pull dies") and assert the typed
recovery contract, instead of soaking random SIGKILLs and hoping:

* ``once``        — fire on the first hit only
* ``nth:K``       — fire on the K-th hit only (1-based)
* ``every:K``     — fire on every K-th hit
* ``always``      — fire on every hit (alias for ``every:1``)
* ``prob:P[:S]``  — fire with probability P from a seeded RNG
  (seed S, default 0) — reproducible "random" faults

Configuration, in precedence order:

1. Runtime test API: :func:`inject` / :func:`clear` (same process only).
2. ``RT_FAULTS`` env var — comma-separated ``point=schedule`` pairs,
   inherited by spawned worker/raylet processes, e.g.
   ``RT_FAULTS=transfer.pull.recv=once,gcs.rpc.send=nth:3``.
3. The ``testing_faults`` config flag (same syntax), so a test cluster
   can arm children via ``system_config`` without touching os.environ.

:data:`FAULT_POINTS` is the committed manifest of every point threaded
through the codebase; ``tests/test_fault_injection.py`` cross-checks it
against the actual ``fault_point("...")`` call sites so the two cannot
drift.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Dict, Optional

__all__ = [
    "FAULT_POINTS",
    "FaultInjected",
    "fault_point",
    "inject",
    "clear",
    "configure",
    "hits",
    "fired",
    "active_points",
]


class FaultInjected(ConnectionError):
    """Raised at an armed fault point.

    Subclasses ``ConnectionError`` (→ ``OSError``) so every transport
    retry path that already catches ``OSError``/``ConnectionError``
    treats an injected fault exactly like a torn connection.
    """

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point}")
        self.point = point

    def __reduce__(self):  # survive pickling across process boundaries
        return (FaultInjected, (self.point,))


# The committed manifest: name -> where it fires (one line each).  Tests
# walk this dict; adding a fault_point() call site without an entry here
# (or vice versa) fails tests/test_fault_injection.py.
FAULT_POINTS: Dict[str, str] = {
    "transfer.server.send": (
        "TransferServer response path, before any bytes of the payload "
        "are written back — the puller sees a dead/early-EOF holder"),
    "transfer.pull.connect": (
        "pull_object leader, before connecting to the holder — "
        "connection refused / holder unreachable"),
    "transfer.pull.recv": (
        "pull_object leader, after the request is sent and before the "
        "response header is read — mid-pull holder death"),
    "transfer.pull.dedup_wait": (
        "pull_object follower, before waiting on the leader's event — "
        "exercises the follower deadline/error propagation path"),
    "gcs.rpc.send": (
        "GcsClient, before dispatching any RPC to the control plane — "
        "GCS unreachable / failover window"),
    "raylet.lease.request": (
        "NormalTaskSubmitter, before sending request_worker_lease(s) "
        "to a raylet — raylet died before granting"),
    "raylet.lease.return": (
        "NormalTaskSubmitter, before sending return_worker to a raylet "
        "— raylet died holding our lease"),
    "worker.task.push": (
        "NormalTaskSubmitter, before pushing a task to a leased worker "
        "— worker crashed between lease grant and task delivery"),
    "graph.channel.write": (
        "ShmChannel.write, before serializing the payload into the "
        "mutable shm segment — a compiled-pipeline hop dies mid-stream "
        "(both stage exec loops and the driver's execute() cross it)"),
    "graph.channel.read": (
        "ShmChannel.read, before waiting on the segment's version bump — "
        "the reading end of a pipeline hop dies / loses the segment"),
    "rl.fragment.push": (
        "Podracer Sebulba runner, after sealing a fragment batch and "
        "before pushing its ref into the runner's fragment channel — "
        "the handoff dies; the runner counts the drop and keeps acting"),
    "rl.params.broadcast": (
        "Podracer Sebulba learner, before writing a weights broadcast "
        "to one runner's param channel — that runner misses the version "
        "(policy lag grows) and catches up on the next broadcast"),
    "spill.write": (
        "ShmObjectStore spill engine, before writing a spill file — "
        "disk full / IO error on the spill path"),
    "pubsub.publish": (
        "Publisher.publish — the message is silently DROPPED (not "
        "raised) to model a lost control-plane event"),
    "serve.replica.call": (
        "Serve replica harness, before invoking the user callable for a "
        "unary or micro-batched request — the whole call fails like a "
        "torn transport; the proxy re-routes to a fresh replica"),
    "serve.replica.stream": (
        "Serve replica harness, before the streaming generator yields "
        "its first item — mid-stream replica death; the proxy surfaces "
        "a clean `event: error` SSE frame"),
    "serve.proxy.write": (
        "ProxyActor HTTP write path, before response/chunk bytes hit "
        "the socket — the client connection tears mid-write; the "
        "listener and other connections stay healthy"),
    "serve.controller.probe": (
        "ServeController health probe, before pinging a replica — a "
        "lost/slow probe; flap damping requires failure_threshold "
        "consecutive misses before ejecting the replica"),
    "serve.llm.prefix_match": (
        "LLM engine admission, before walking the radix prefix cache — "
        "the lookup is skipped and the request degrades to a COLD "
        "prefill with a typed counter bump (prefix_match_faults); no "
        "shared block is touched and admission never hangs"),
    "serve.llm.prefix_insert": (
        "LLM engine, before sharing a finished prefill's blocks into "
        "the radix tree — the insert is skipped whole with a typed "
        "counter bump (prefix_insert_faults); the blocks stay owned by "
        "the slot, so nothing is ever half-inserted or corrupted"),
}

# --------------------------------------------------------------------------
# State.  _ACTIVE is the hot-path gate: fault_point() reads it and returns
# before touching anything else.  All mutation happens under _lock.
# --------------------------------------------------------------------------

_ACTIVE = False
_lock = threading.Lock()
_schedules: Dict[str, "_Schedule"] = {}
_hit_counts: Dict[str, int] = {}
_fired_counts: Dict[str, int] = {}


class _Schedule:
    """One armed fault point's firing rule.  Mutated under _lock only."""

    __slots__ = ("spec", "kind", "k", "prob", "rng", "hits", "done")

    def __init__(self, spec: str):
        self.spec = spec
        self.hits = 0
        self.done = False
        kind, _, rest = spec.partition(":")
        kind = kind.strip().lower()
        if kind == "once":
            self.kind, self.k = "nth", 1
        elif kind == "always":
            self.kind, self.k = "every", 1
        elif kind == "nth":
            self.kind, self.k = "nth", int(rest)
            if self.k < 1:
                raise ValueError(f"nth:K needs K >= 1, got {spec!r}")
        elif kind == "every":
            self.kind, self.k = "every", int(rest)
            if self.k < 1:
                raise ValueError(f"every:K needs K >= 1, got {spec!r}")
        elif kind == "prob":
            p, _, seed = rest.partition(":")
            self.kind = "prob"
            self.prob = float(p)
            if not 0.0 <= self.prob <= 1.0:
                raise ValueError(f"prob:P needs 0 <= P <= 1, got {spec!r}")
            self.rng = random.Random(int(seed) if seed else 0)
            return
        else:
            raise ValueError(
                f"unknown fault schedule {spec!r} "
                "(want once | nth:K | every:K | always | prob:P[:seed])")

    def should_fire(self) -> bool:
        self.hits += 1
        if self.kind == "nth":
            if self.done:
                return False
            if self.hits == self.k:
                self.done = True
                return True
            return False
        if self.kind == "every":
            return self.hits % self.k == 0
        return self.rng.random() < self.prob  # prob


def _parse_spec_string(spec: str) -> Dict[str, "_Schedule"]:
    """``"a=once,b=nth:3"`` -> {point: schedule}.  Unknown point names are
    rejected loudly — a typo'd RT_FAULTS that silently arms nothing is a
    chaos test that silently tests nothing."""
    out: Dict[str, _Schedule] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, sched = part.partition("=")
        name = name.strip()
        if not sep:
            raise ValueError(f"bad RT_FAULTS entry {part!r} (want point=schedule)")
        if name not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {name!r}; known: {sorted(FAULT_POINTS)}")
        out[name] = _Schedule(sched.strip())
    return out


def configure(spec: str) -> None:
    """Replace the armed set from a spec string (RT_FAULTS syntax)."""
    global _ACTIVE
    parsed = _parse_spec_string(spec)
    with _lock:
        _schedules.clear()
        _schedules.update(parsed)
        _ACTIVE = bool(_schedules)


def inject(point: str, schedule: str = "once") -> None:
    """Runtime test API: arm one fault point in this process."""
    global _ACTIVE
    if point not in FAULT_POINTS:
        raise ValueError(
            f"unknown fault point {point!r}; known: {sorted(FAULT_POINTS)}")
    sched = _Schedule(schedule)
    with _lock:
        _schedules[point] = sched
        _ACTIVE = True


def clear() -> None:
    """Disarm everything and reset counters (test teardown)."""
    global _ACTIVE
    with _lock:
        _schedules.clear()
        _hit_counts.clear()
        _fired_counts.clear()
        _ACTIVE = False


def hits(point: str) -> int:
    """How many times an armed ``fault_point(point)`` was reached."""
    with _lock:
        return _hit_counts.get(point, 0)


def fired(point: str) -> int:
    """How many times ``fault_point(point)`` actually raised."""
    with _lock:
        return _fired_counts.get(point, 0)


def active_points() -> Dict[str, str]:
    """Currently armed {point: spec} (for diagnostics)."""
    with _lock:
        return {name: s.spec for name, s in _schedules.items()}


def fault_point(name: str) -> None:
    """Declare a named cross-process boundary; raise if a fault is armed.

    Production fast path: one global read, one truth test, return.
    """
    if not _ACTIVE:
        return
    with _lock:
        sched = _schedules.get(name)
        if sched is None:
            return
        _hit_counts[name] = _hit_counts.get(name, 0) + 1
        if not sched.should_fire():
            return
        _fired_counts[name] = _fired_counts.get(name, 0) + 1
    raise FaultInjected(name)


def _load_from_env() -> None:
    """Arm from RT_FAULTS / testing_faults at import (each process)."""
    spec = os.environ.get("RT_FAULTS", "")
    if not spec:
        # Config flag path (system_config propagation).  Import lazily and
        # defensively: faults must be importable before/without config.
        try:
            from ray_tpu.common.config import GLOBAL_CONFIG
            spec = GLOBAL_CONFIG.get("testing_faults") or ""
        except Exception:  # noqa: BLE001 - config unavailable = faults off
            spec = ""
    if spec:
        configure(spec)


_load_from_env()
