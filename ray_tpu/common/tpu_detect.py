"""TPU metadata autodetection.

Reference: ``python/ray/_private/accelerators/tpu.py`` — chips detected via
``TPU_ACCELERATOR_TYPE``/GCE metadata (``:16-30``), pod worker counts from
the accelerator type (``:313``), slice name + worker index advertised as
scheduling labels (``:338-374``). Here the same environment surface feeds
first-class ``TPU`` resources and ``rt.io/tpu-*`` labels automatically, so
``SLICE_PACK`` placement works without hand-set ``num_tpus``.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Optional

# chips per HOST by accelerator generation (public TPU VM shapes: v2/v3
# are 4-chip half-boards per VM, v4/v5p 4, v5e/v6e up to 8 for the
# single-host shapes and 4 for pod slices).
_DEFAULT_CHIPS_PER_HOST = 4
_SINGLE_HOST_V5E = {"v5litepod-1": 1, "v5litepod-4": 4, "v5litepod-8": 8,
                    "v6e-1": 1, "v6e-4": 4, "v6e-8": 8}


def _chips_from_accelerator_type(acc: str) -> Optional[int]:
    """'v5litepod-16' → chips on THIS host (not the whole slice)."""
    acc = acc.strip().lower()
    if not acc:
        return None
    if acc in _SINGLE_HOST_V5E:
        return _SINGLE_HOST_V5E[acc]
    try:
        total = int(acc.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return None
    return min(total, _DEFAULT_CHIPS_PER_HOST)


def detect() -> Dict[str, object]:
    """Best-effort local TPU discovery from the environment.

    Returns {"chips": float, "topology": str|None, "slice_name": str|None,
    "worker_id": int|None}. Never initializes jax (that would claim the
    chips before the worker that should own them)."""
    chips: Optional[float] = None
    topology = (os.environ.get("TPU_ACCELERATOR_TYPE")
                or os.environ.get("ACCELERATOR_TYPE") or None)

    if os.environ.get("TPU_VISIBLE_CHIPS"):
        chips = float(len(os.environ["TPU_VISIBLE_CHIPS"].split(",")))
    if chips is None and topology:
        got = _chips_from_accelerator_type(topology)
        if got is not None:
            chips = float(got)
    if chips is None:
        # device files exist on real TPU VMs (reference tpu.py glob)
        accel = glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*")
        if accel:
            chips = float(len(accel))
    if chips is None:
        import sys

        if "jax" in sys.modules:  # already initialized: safe to ask
            try:
                import jax

                chips = float(len([d for d in jax.devices()
                                   if d.platform != "cpu"]))
            except Exception:  # noqa: BLE001
                chips = 0.0
    worker_id = None
    if os.environ.get("TPU_WORKER_ID"):
        try:
            worker_id = int(os.environ["TPU_WORKER_ID"])
        except ValueError:
            pass
    slice_name = (os.environ.get("TPU_NAME")
                  or os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")[0]
                  or None)
    return {"chips": float(chips or 0.0), "topology": topology,
            "slice_name": slice_name, "worker_id": worker_id}


def defer_tpu_preload(env: dict) -> dict:
    """Stash the axon/PJRT boot env vars so a freshly forked process does
    NOT connect to the TPU at interpreter startup (the sitecustomize boot
    costs seconds and blocks entirely when the tunnel is busy). The stashed
    vars are restored by the worker when a TPU lease actually lands on it
    (core_worker h_set_visible_devices), or by user code calling
    restore_tpu_preload()."""
    if env.get("PALLAS_AXON_POOL_IPS"):
        env["RT_DEFERRED_PALLAS_AXON_POOL_IPS"] = env.pop(
            "PALLAS_AXON_POOL_IPS")
        if "axon" in env.get("JAX_PLATFORMS", ""):
            # axon is unregistered until the deferred boot runs; leaving the
            # platform pinned would make a plain jax import raise.
            env["RT_DEFERRED_JAX_PLATFORMS"] = env.pop("JAX_PLATFORMS")
    return env
