"""Resource model with fractional fixed-point accounting and first-class TPU.

Follows the reference's scheduling resource model
(src/ray/common/scheduling/cluster_resource_data.h:37, resource_instance_set.h:25,
fixed_point.h:25) with one deliberate divergence: **TPU is a predefined resource**
(the reference keeps TPU as a string custom resource set up by an accelerator
plugin, python/ray/_private/accelerators/tpu.py) and nodes carry ICI-topology
labels (slice name, worker index, topology) so placement policies can
gang-schedule SPMD groups onto one slice.

All quantities are fixed-point with 1e-4 resolution so fractional resources
(e.g. num_tpus=0.25) have exact arithmetic.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Optional

RESOLUTION = 10_000

# Predefined resource names (reference: scheduling_ids.h:32 PredefinedResourcesEnum,
# which has CPU/MEM/GPU/OBJECT_STORE_MEM; we add TPU).
CPU = "CPU"
MEM = "memory"
GPU = "GPU"
TPU = "TPU"
OBJECT_STORE_MEM = "object_store_memory"
PREDEFINED = (CPU, MEM, GPU, TPU, OBJECT_STORE_MEM)

# Node labels with framework meaning (TPU topology; reference expresses the
# equivalent via `TPU-<pod_type>-head` custom resources, tpu.py:338-374).
LABEL_SLICE_NAME = "rt.io/tpu-slice"
LABEL_SLICE_TOPOLOGY = "rt.io/tpu-topology"
LABEL_SLICE_WORKER_INDEX = "rt.io/tpu-worker-index"
LABEL_NODE_ID = "rt.io/node-id"

# Unit-instance resources: allocation happens per whole device instance when
# the request is an integer (reference: NodeResourceInstanceSet).
UNIT_INSTANCE_RESOURCES = (GPU, TPU)


def to_fixed(value: float | int) -> int:
    return round(value * RESOLUTION)


def from_fixed(value: int) -> float:
    if value % RESOLUTION == 0:
        return value // RESOLUTION
    return value / RESOLUTION


class ResourceSet:
    """Immutable-ish map of resource name -> fixed-point quantity (>0 entries only)."""

    __slots__ = ("_fixed",)

    def __init__(self, resources: Optional[Mapping[str, float]] = None, _fixed=None):
        if _fixed is not None:
            self._fixed: Dict[str, int] = {k: v for k, v in _fixed.items() if v > 0}
        else:
            self._fixed = {}
            for name, qty in (resources or {}).items():
                if qty < 0:
                    raise ValueError(f"negative resource {name}={qty}")
                f = to_fixed(qty)
                if f > 0:
                    self._fixed[name] = f

    @classmethod
    def _from_fixed(cls, fixed: Dict[str, int]) -> "ResourceSet":
        return cls(_fixed=fixed)

    def get(self, name: str) -> float:
        return from_fixed(self._fixed.get(name, 0))

    def get_fixed(self, name: str) -> int:
        return self._fixed.get(name, 0)

    def names(self) -> Iterable[str]:
        return self._fixed.keys()

    def is_empty(self) -> bool:
        return not self._fixed

    def to_dict(self) -> Dict[str, float]:
        return {k: from_fixed(v) for k, v in self._fixed.items()}

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other._fixed.get(k, 0) >= v for k, v in self._fixed.items())

    def __add__(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._fixed)
        for k, v in other._fixed.items():
            out[k] = out.get(k, 0) + v
        return ResourceSet._from_fixed(out)

    def __sub__(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._fixed)
        for k, v in other._fixed.items():
            out[k] = out.get(k, 0) - v
            if out[k] < 0:
                raise ValueError(f"resource {k} would go negative")
        return ResourceSet._from_fixed(out)

    def __eq__(self, other) -> bool:
        return isinstance(other, ResourceSet) and self._fixed == other._fixed

    def __repr__(self) -> str:
        return f"ResourceSet({self.to_dict()})"

    def __reduce__(self):
        return (_resource_set_from_dict, (self.to_dict(),))


def _resource_set_from_dict(d):
    return ResourceSet(d)


class LabelSelector:
    """Node-label constraint (reference: label_selector.h:56).

    Supported ops: ``in``, ``!in``, ``exists``, ``!exists`` expressed as a dict
    {key: spec} where spec is a string value ("v" / "!v") or list of values.
    """

    def __init__(self, selector: Optional[Mapping[str, object]] = None):
        self._selector = dict(selector or {})

    def matches(self, labels: Mapping[str, str]) -> bool:
        for key, spec in self._selector.items():
            if spec == "exists":
                if key not in labels:
                    return False
            elif spec == "!exists":
                if key in labels:
                    return False
            elif isinstance(spec, str):
                if spec.startswith("!"):
                    if labels.get(key) == spec[1:]:
                        return False
                elif labels.get(key) != spec:
                    return False
            elif isinstance(spec, (list, tuple, set)):
                if labels.get(key) not in spec:
                    return False
            else:
                raise ValueError(f"bad label selector spec {key}={spec!r}")
        return True

    def is_empty(self) -> bool:
        return not self._selector

    def to_dict(self):
        return dict(self._selector)

    def __repr__(self):
        return f"LabelSelector({self._selector})"


class NodeResources:
    """A node's total/available resources + labels, with per-instance accounting
    for unit-instance resources (TPU/GPU chips)."""

    def __init__(
        self,
        total: Mapping[str, float],
        labels: Optional[Mapping[str, str]] = None,
    ):
        self.total = ResourceSet(total)
        self.available = ResourceSet(total)
        self.labels: Dict[str, str] = dict(labels or {})
        # chip-index -> fixed-point free fraction, for TPU/GPU visibility assignment
        self._instances: Dict[str, List[int]] = {}
        for res in UNIT_INSTANCE_RESOURCES:
            n = self.total.get(res)
            if n and float(n).is_integer():
                self._instances[res] = [RESOLUTION] * int(n)

    # -- queries --
    def is_feasible(self, request: "ResourceRequest") -> bool:
        """Could this request EVER fit on an empty node (capacity + labels)?"""
        return request.resources.is_subset_of(self.total) and request.label_selector.matches(
            self.labels
        )

    def is_available(self, request: "ResourceRequest") -> bool:
        return request.resources.is_subset_of(self.available) and request.label_selector.matches(
            self.labels
        )

    def utilization(self) -> float:
        worst = 0.0
        for name in self.total.names():
            t = self.total.get_fixed(name)
            a = self.available.get_fixed(name)
            if t > 0:
                worst = max(worst, (t - a) / t)
        return worst

    # -- mutation --
    def allocate(self, request: "ResourceRequest") -> Optional[Dict[str, List[int]]]:
        """Subtract the request; returns {resource: [chip indices]} for unit
        resources (used to set TPU_VISIBLE_CHIPS), or None if it doesn't fit."""
        if not self.is_available(request):
            return None
        # Two-phase: tentatively pick instance slots for every unit resource,
        # then apply atomically — a partial failure must not leak zeroed slots.
        plan: List[tuple] = []  # (insts, index, new_value)
        assignment: Dict[str, List[int]] = {}
        for res, insts in self._instances.items():
            need = request.resources.get_fixed(res)
            if need == 0:
                continue
            picked: List[int] = []
            if need % RESOLUTION == 0:
                want = need // RESOLUTION
                for i, free in enumerate(insts):
                    if free == RESOLUTION and len(picked) < want:
                        picked.append(i)
                        plan.append((insts, i, 0))
                if len(picked) < want:
                    # aggregate has capacity but chips are fragmented by
                    # fractional allocations: whole-chip request can't be met
                    return None
            else:
                # fractional: carve from the first instance with enough room
                for i, free in enumerate(insts):
                    if free >= need:
                        picked.append(i)
                        plan.append((insts, i, free - need))
                        break
                else:
                    return None
            assignment[res] = picked
        self.available = self.available - request.resources
        for insts, i, new_value in plan:
            insts[i] = new_value
        return assignment

    def free(self, request: "ResourceRequest", assignment: Optional[Dict[str, List[int]]] = None):
        self.available = self.available + request.resources
        for res, picked in (assignment or {}).items():
            insts = self._instances.get(res)
            if insts is None:
                continue
            need = request.resources.get_fixed(res)
            if need % RESOLUTION == 0:
                for i in picked:
                    insts[i] = RESOLUTION
            elif picked:
                insts[picked[0]] += need

    def snapshot(self) -> dict:
        return {
            "total": self.total.to_dict(),
            "available": self.available.to_dict(),
            "labels": dict(self.labels),
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "NodeResources":
        nr = cls(snap["total"], snap.get("labels"))
        nr.available = ResourceSet(snap["available"])
        return nr

    def __repr__(self):
        return f"NodeResources(total={self.total.to_dict()}, avail={self.available.to_dict()})"


class ResourceRequest:
    """What a task/actor/bundle demands (reference: cluster_resource_data.h:37)."""

    def __init__(
        self,
        resources: Optional[Mapping[str, float]] = None,
        label_selector: Optional[Mapping[str, object]] = None,
    ):
        self.resources = ResourceSet(resources)
        self.label_selector = LabelSelector(label_selector)

    def is_empty(self) -> bool:
        return self.resources.is_empty() and self.label_selector.is_empty()

    def to_dict(self) -> dict:
        return {
            "resources": self.resources.to_dict(),
            "label_selector": self.label_selector.to_dict(),
        }

    @classmethod
    def from_dict(cls, d) -> "ResourceRequest":
        return cls(d.get("resources"), d.get("label_selector"))

    def shape_key(self) -> tuple:
        """Hashable key grouping equivalent requests (lease pooling)."""
        return (
            tuple(sorted(self.resources.to_dict().items())),
            tuple(sorted((k, str(v)) for k, v in self.label_selector.to_dict().items())),
        )

    def __repr__(self):
        return f"ResourceRequest({self.resources.to_dict()}, labels={self.label_selector.to_dict()})"
