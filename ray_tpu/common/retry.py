"""One retry/backoff/deadline policy for every cross-process path.

Before this module each boundary rolled its own loop: the transfer pull
chain used fixed 30 s socket timeouts, the task submitter slept a flat
0.3 s once, the RPC client hand-computed exponential backoff, and nested
calls STACKED their budgets — a pull inside a fetch inside a task could
wait 30 s per layer.  ``RetryPolicy`` + ``Deadline`` replace all of
that: exponential backoff with full jitter, an attempt cap, and one
deadline budget threaded through nested calls so every layer shares the
same clock.

Typical shapes::

    # explicit loop (callers that need per-attempt logic)
    policy = RetryPolicy(max_attempts=5, deadline=Deadline(10.0))
    for attempt in policy:                    # 1, 2, 3, ...
        try:
            return do_rpc(timeout=policy.deadline.remaining(cap=5.0))
        except ConnectionError as e:
            if not policy.sleep(attempt):     # backs off, or gives up
                raise

    # wrapped call
    policy.call(lambda: do_rpc(), retry_on=(ConnectionError,))
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type

__all__ = ["Deadline", "RetryPolicy", "DEFAULT_BASE_S", "DEFAULT_CAP_S"]

DEFAULT_BASE_S = 0.05
DEFAULT_CAP_S = 2.0


class Deadline:
    """An absolute budget on the monotonic clock, passed DOWN call chains.

    ``Deadline(30.0)`` means "this whole operation — every nested retry
    included — has 30 s".  Callees take ``deadline.remaining()`` for
    their per-step timeouts instead of inventing fresh 30 s windows.
    ``Deadline(None)`` is the explicit "no budget" value so signatures
    can always take a Deadline.
    """

    __slots__ = ("_at",)

    def __init__(self, timeout_s: Optional[float] = None):
        self._at = None if timeout_s is None else time.monotonic() + timeout_s

    @classmethod
    def at(cls, monotonic_deadline: Optional[float]) -> "Deadline":
        d = cls(None)
        d._at = monotonic_deadline
        return d

    @property
    def unbounded(self) -> bool:
        return self._at is None

    def remaining(self, cap: Optional[float] = None,
                  floor: float = 0.0) -> Optional[float]:
        """Seconds left (>= floor), or ``cap`` / None when unbounded.

        ``cap`` bounds a single step inside the budget (e.g. one socket
        timeout); ``floor`` keeps an almost-expired budget from handing
        a callee a zero/negative timeout it would misread as "forever".
        """
        if self._at is None:
            return cap
        left = max(floor, self._at - time.monotonic())
        return left if cap is None else min(left, cap)

    def expired(self) -> bool:
        return self._at is not None and time.monotonic() >= self._at

    def __repr__(self):
        if self._at is None:
            return "Deadline(unbounded)"
        return f"Deadline({self._at - time.monotonic():.3f}s left)"


class RetryPolicy:
    """Exponential backoff, full jitter, attempt cap, shared deadline.

    ``max_attempts`` counts TRIES (first call included); 0 = unlimited
    (bounded by the deadline alone).  Backoff before retry N (1-based)
    is uniform in ``[0, min(cap_s, base_s * 2**(N-1))]`` — full jitter,
    the variant that decorrelates a thundering herd of retriers (every
    fixed-sleep loop this replaces woke all waiters on the same tick).
    The sleep is additionally clipped to the deadline's remaining
    budget, and a retry that could only start AT the deadline is not
    attempted at all.
    """

    def __init__(self, max_attempts: int = 0, *,
                 base_s: float = DEFAULT_BASE_S,
                 cap_s: float = DEFAULT_CAP_S,
                 deadline: Optional[Deadline] = None,
                 rng: Optional[random.Random] = None):
        if max_attempts < 0:
            raise ValueError("max_attempts must be >= 0 (0 = unlimited)")
        self.max_attempts = max_attempts
        self.base_s = base_s
        self.cap_s = cap_s
        self.deadline = deadline if deadline is not None else Deadline(None)
        self._rng = rng if rng is not None else random

    # -- core decision -----------------------------------------------------

    def next_delay(self, attempt: int) -> Optional[float]:
        """Backoff before retry ``attempt`` (1-based count of FAILED
        tries so far), or None when the policy is exhausted."""
        if self.max_attempts and attempt >= self.max_attempts:
            return None
        if self.deadline.expired():
            return None
        delay = self._rng.uniform(
            0.0, min(self.cap_s, self.base_s * (2 ** (attempt - 1))))
        left = self.deadline.remaining()
        if left is not None:
            if left <= 0:
                return None
            delay = min(delay, left)
        return delay

    def __iter__(self) -> Iterator[int]:
        """Yield attempt numbers 1, 2, ... while the policy allows."""
        attempt = 0
        while True:
            attempt += 1
            if self.max_attempts and attempt > self.max_attempts:
                return
            if attempt > 1 and self.deadline.expired():
                return
            yield attempt

    # -- sleep helpers (loop style) ---------------------------------------

    def sleep(self, attempt: int) -> bool:
        """Back off before retry ``attempt``; False = give up instead."""
        delay = self.next_delay(attempt)
        if delay is None:
            return False
        if delay > 0:
            time.sleep(delay)
        return True

    async def asleep(self, attempt: int) -> bool:
        delay = self.next_delay(attempt)
        if delay is None:
            return False
        if delay > 0:
            await asyncio.sleep(delay)
        return True

    # -- wrapped-call helpers ---------------------------------------------

    def call(self, fn: Callable, *,
             retry_on: Tuple[Type[BaseException], ...] = (ConnectionError,
                                                          TimeoutError)):
        """Run ``fn()`` under this policy; re-raises the last error."""
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except retry_on:
                if not self.sleep(attempt):
                    raise

    async def call_async(self, fn: Callable, *,
                         retry_on: Tuple[Type[BaseException], ...] = (
                             ConnectionError, TimeoutError)):
        """Run ``await fn()`` under this policy; re-raises the last error."""
        attempt = 0
        while True:
            attempt += 1
            try:
                return await fn()
            except retry_on:
                if not await self.asleep(attempt):
                    raise
