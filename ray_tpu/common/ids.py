"""Deterministic nested binary IDs.

Design follows the reference's ID nesting scheme (src/ray/common/id.h:130-264):
``JobID ⊂ ActorID ⊂ TaskID ⊂ ObjectID`` — each wider ID embeds the narrower one
so ownership and provenance can be recovered from the bytes alone.  Object IDs
are *computed*, not random: they derive from the owning task plus a return /
put index, which is what makes lineage reconstruction possible (re-executing
the creating task regenerates the same ObjectID).

Sizes (bytes):
    JobID    4
    ActorID  4 (job) + 12 (unique)            = 16
    TaskID   16 (actor id) + 8 (unique)       = 24
    ObjectID 24 (task id) + 4 (index)         = 28

A "nil" ID is all 0xff, as in the reference.
"""

from __future__ import annotations

import hashlib
import os
import threading

_JOB_ID_SIZE = 4
_ACTOR_UNIQUE_SIZE = 12
_ACTOR_ID_SIZE = _JOB_ID_SIZE + _ACTOR_UNIQUE_SIZE
_TASK_UNIQUE_SIZE = 8
_TASK_ID_SIZE = _ACTOR_ID_SIZE + _TASK_UNIQUE_SIZE
_OBJECT_INDEX_SIZE = 4
_OBJECT_ID_SIZE = _TASK_ID_SIZE + _OBJECT_INDEX_SIZE

# Object index space is split: indices >= PUT_INDEX_BASE are ray.put()s,
# below are task returns (reference: ObjectID::FromIndex semantics).
PUT_INDEX_BASE = 1 << 31


class BaseID:
    """Immutable fixed-width binary ID."""

    SIZE = 0
    __slots__ = ("_bytes", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = bytes(binary)
        self._hash = None

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self) -> int:
        # IDs key every hot-path dict (refcounts, pending calls, dedup);
        # an actor call hashes IDs ~18 times end-to-end, so cache it.
        h = self._hash
        if h is None:
            h = self._hash = hash((type(self).__name__, self._bytes))
        return h

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(_JOB_ID_SIZE, "little"))

    def to_int(self) -> int:
        return int.from_bytes(self._bytes, "little")


class UniqueID(BaseID):
    """Free-standing 16-byte ID (nodes, workers, placement groups, clients)."""

    SIZE = 16


class NodeID(UniqueID):
    pass


class WorkerID(UniqueID):
    pass


class PlacementGroupID(UniqueID):
    pass


class ActorID(BaseID):
    SIZE = _ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID, parent_task_id: "TaskID", actor_creation_index: int) -> "ActorID":
        h = hashlib.sha256()
        h.update(parent_task_id.binary())
        h.update(actor_creation_index.to_bytes(4, "little"))
        return cls(job_id.binary() + h.digest()[:_ACTOR_UNIQUE_SIZE])

    @classmethod
    def nil_from_job(cls, job_id: JobID) -> "ActorID":
        """The 'no actor' actor id still carrying the job: used for normal tasks."""
        return cls(job_id.binary() + b"\xff" * _ACTOR_UNIQUE_SIZE)

    def job_id(self) -> JobID:
        return JobID(self._bytes[:_JOB_ID_SIZE])


class TaskID(BaseID):
    SIZE = _TASK_ID_SIZE

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(ActorID.nil_from_job(job_id).binary() + b"\x00" * _TASK_UNIQUE_SIZE)

    @classmethod
    def for_normal_task(cls, job_id: JobID, parent_task_id: "TaskID", task_index: int) -> "TaskID":
        h = hashlib.sha256()
        h.update(parent_task_id.binary())
        h.update(task_index.to_bytes(8, "little"))
        return cls(
            ActorID.nil_from_job(job_id).binary() + h.digest()[:_TASK_UNIQUE_SIZE]
        )

    @classmethod
    def for_actor_creation_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + b"\x00" * _TASK_UNIQUE_SIZE)

    @classmethod
    def for_actor_task(
        cls, actor_id: ActorID, parent_task_id: "TaskID", task_index: int
    ) -> "TaskID":
        h = hashlib.sha256()
        h.update(parent_task_id.binary())
        h.update(task_index.to_bytes(8, "little"))
        return cls(actor_id.binary() + h.digest()[:_TASK_UNIQUE_SIZE])

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[:_ACTOR_ID_SIZE])

    def job_id(self) -> JobID:
        return JobID(self._bytes[:_JOB_ID_SIZE])


class ObjectID(BaseID):
    SIZE = _OBJECT_ID_SIZE

    @classmethod
    def from_index(cls, task_id: TaskID, index: int) -> "ObjectID":
        """Return-value object: index is 1-based return position."""
        return cls(task_id.binary() + index.to_bytes(_OBJECT_INDEX_SIZE, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls.from_index(task_id, PUT_INDEX_BASE + put_index)

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:_TASK_ID_SIZE])

    def job_id(self) -> JobID:
        return JobID(self._bytes[:_JOB_ID_SIZE])

    def index(self) -> int:
        return int.from_bytes(self._bytes[_TASK_ID_SIZE:], "little")

    def is_put(self) -> bool:
        return self.index() >= PUT_INDEX_BASE


class _Counter:
    """Thread-safe monotonically increasing counter."""

    def __init__(self, start: int = 0):
        self._value = start
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
