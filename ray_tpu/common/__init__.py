from . import ids, resources, status  # noqa: F401
from .config import GLOBAL_CONFIG  # noqa: F401
