"""Task specification — the unit shipped from submitter to executor.

Equivalent of the reference's ``TaskSpecification``
(src/ray/common/task/task_spec.h:258): function descriptor, serialized args
(inline values or ObjectID references), resource demand, scheduling strategy,
and retry policy.  Serialized with cloudpickle for function payloads and plain
pickle-able dataclasses for metadata.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID
from .resources import ResourceRequest


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2
    DRIVER_TASK = 3


@dataclass
class FunctionDescriptor:
    """Language-agnostic function identity (module, qualname, payload hash)."""

    module: str
    qualname: str
    function_hash: bytes = b""

    def key(self) -> Tuple[str, str, bytes]:
        return (self.module, self.qualname, self.function_hash)


class SchedulingStrategy:
    """Base scheduling strategy (reference: scheduling_strategy proto)."""


@dataclass
class DefaultStrategy(SchedulingStrategy):
    pass


@dataclass
class SpreadStrategy(SchedulingStrategy):
    pass


@dataclass
class NodeAffinityStrategy(SchedulingStrategy):
    node_id: NodeID
    soft: bool = False


@dataclass
class NodeLabelStrategy(SchedulingStrategy):
    hard: Dict[str, object] = field(default_factory=dict)
    soft: Dict[str, object] = field(default_factory=dict)


@dataclass
class PlacementGroupStrategy(SchedulingStrategy):
    placement_group_id: PlacementGroupID
    bundle_index: int = -1
    capture_child_tasks: bool = False


@dataclass
class TaskArg:
    """Either an inline value (bytes) or a reference to an object."""

    is_inline: bool
    value: Optional[bytes] = None
    object_id: Optional[ObjectID] = None
    owner: Optional[WorkerID] = None
    owner_address: Optional[Tuple[str, int]] = None
    # Unique id of this by-ref handoff; the owner's transit guard is keyed on
    # it so acks are idempotent under retries/races (see worker.py borrow
    # protocol).
    handoff_token: Optional[bytes] = None

    @classmethod
    def inline(cls, value: bytes) -> "TaskArg":
        return cls(is_inline=True, value=value)

    @classmethod
    def by_ref(cls, object_id: ObjectID, owner: Optional[WorkerID] = None) -> "TaskArg":
        return cls(is_inline=False, object_id=object_id, owner=owner)


@dataclass
class _FastArgs:
    """Single-pickle argument bundle for the native actor-call fast path:
    the whole (args, kwargs) is ONE serialized value instead of one
    TaskArg frame per argument."""

    args: tuple
    kwargs: dict


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    function: FunctionDescriptor
    serialized_func: Optional[bytes]  # cloudpickled callable (None => registry lookup)
    args: List[TaskArg]
    num_returns: int
    required_resources: ResourceRequest
    scheduling_strategy: SchedulingStrategy = field(default_factory=DefaultStrategy)
    max_retries: int = 0
    retry_exceptions: bool = False
    parent_task_id: Optional[TaskID] = None
    caller_worker_id: Optional[WorkerID] = None
    caller_address: Optional[Tuple[str, int]] = None
    # actor fields
    actor_id: Optional[ActorID] = None
    actor_method_name: Optional[str] = None
    sequence_number: int = 0
    max_restarts: int = 0
    max_concurrency: int = 1
    # Streaming generator returns (reference: core_worker.proto:430
    # ReportGeneratorItemReturns): yielded items are reported to the owner
    # one by one under ObjectID.from_index(task_id, i+1); num_returns is 0.
    streaming: bool = False
    # runtime env / misc
    runtime_env: Optional[dict] = None
    name: str = ""
    # content hash of runtime_env, computed ONCE at submit time (hashing
    # walks working_dir trees — far too hot for shape_key, which runs on
    # the IO loop for every task)
    runtime_env_hash: Optional[str] = None
    # tracing context of the submitting span ({trace_id, span_id}), so
    # the executing worker's span parents across the process boundary
    # (reference: ray.util.tracing injects the OTel context into task
    # metadata). None when tracing is off — the common case.
    tracing: Optional[dict] = None

    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.from_index(self.task_id, i + 1) for i in range(self.num_returns)]

    def is_actor_task(self) -> bool:
        return self.task_type == TaskType.ACTOR_TASK

    def is_actor_creation(self) -> bool:
        return self.task_type == TaskType.ACTOR_CREATION_TASK

    def dependencies(self) -> List[ObjectID]:
        return [a.object_id for a in self.args if not a.is_inline and a.object_id is not None]

    @classmethod
    def from_fast(cls, blob: bytes) -> "TaskSpec":
        """Rebuild a task from a native fastspec buffer (see
        rpc/native/fastspec.c): v1 = ACTOR_TASK, v2 = NORMAL_TASK (the
        lease-cached dispatch channel's record). Only fields the executee
        reads are populated; the rest hold cheap defaults."""
        if len(blob) > 4 and blob[4] == 2:
            return cls._from_fast_task(blob)
        from ray_tpu.rpc.native import unpack_fastspec

        (task_raw, job_raw, actor_raw, wid_raw, host, method, payload,
         seq, num_returns, port) = unpack_fastspec(blob)
        method_s = method.decode()
        return cls(
            task_id=TaskID(task_raw),
            job_id=JobID(job_raw),
            task_type=TaskType.ACTOR_TASK,
            function=FunctionDescriptor("", method_s),
            serialized_func=None,
            args=[TaskArg.inline(payload)],
            num_returns=num_returns,
            required_resources=ResourceRequest({}),
            actor_id=ActorID(actor_raw),
            actor_method_name=method_s,
            sequence_number=seq,
            caller_worker_id=WorkerID(wid_raw),
            caller_address=(host.decode(), port),
            name=method_s,
        )

    @classmethod
    def _from_fast_task(cls, blob: bytes) -> "TaskSpec":
        """v2 record: a normal task pushed over the native dispatch
        channel. The args payload is ONE pickle of the per-arg inline
        frames (eligibility guarantees every arg was inline)."""
        import pickle as _pickle

        from ray_tpu.rpc.native import unpack_fasttask

        (task_raw, job_raw, wid_raw, host, qualname, func, payload,
         name, num_returns, port) = unpack_fasttask(blob)
        qual_s = qualname.decode()
        return cls(
            task_id=TaskID(task_raw),
            job_id=JobID(job_raw),
            task_type=TaskType.NORMAL_TASK,
            function=FunctionDescriptor("", qual_s),
            serialized_func=func,
            args=[TaskArg.inline(v) for v in _pickle.loads(payload)],
            num_returns=num_returns,
            required_resources=ResourceRequest({}),
            caller_worker_id=WorkerID(wid_raw),
            caller_address=(host.decode(), port),
            # display name travels in the record: task events / errors
            # must report the submit-side name, not the qualname
            name=name.decode() or qual_s,
        )

    def shape_key(self) -> tuple:
        """Lease-pooling key: tasks with the same shape can share leases.
        Runtime env joins the key — a lease's worker is a process forked
        into ONE materialized environment."""
        if self.runtime_env is not None and self.runtime_env_hash is None:
            from ray_tpu.runtime_env.runtime_env import env_hash

            self.runtime_env_hash = env_hash(self.runtime_env)
        return (self.required_resources.shape_key(),
                type(self.scheduling_strategy).__name__,
                self.runtime_env_hash)
