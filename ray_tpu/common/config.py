"""Central config/flag registry.

Mirrors the reference's ``RAY_CONFIG(type, name, default)`` system
(src/ray/common/ray_config_def.h:18): every flag is declared once with a type
and default, can be overridden by the ``RT_<name>`` environment variable, and a
cluster-wide ``system_config`` dict (propagated through the GCS at startup)
takes precedence over defaults but not env vars.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict

_ENV_PREFIX = "RT_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
}


@dataclass
class _Flag:
    name: str
    type: type
    default: Any
    doc: str = ""


class Config:
    """Flag registry with env > system_config > default precedence."""

    def __init__(self):
        self._flags: Dict[str, _Flag] = {}
        self._system_config: Dict[str, Any] = {}
        self._cache: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def declare(self, name: str, type_: type, default: Any, doc: str = "") -> None:
        if name in self._flags:
            raise ValueError(f"flag {name!r} declared twice")
        self._flags[name] = _Flag(name, type_, default, doc)

    def initialize(self, system_config: Dict[str, Any] | str | None) -> None:
        """Apply a cluster-wide system_config (dict or JSON string)."""
        if system_config is None:
            system_config = {}
        if isinstance(system_config, str):
            system_config = json.loads(system_config) if system_config else {}
        with self._lock:
            for key in system_config:
                if key not in self._flags:
                    raise ValueError(f"unknown system_config key {key!r}")
            self._system_config = dict(system_config)
            self._cache.clear()

    def system_config_json(self) -> str:
        return json.dumps(self._system_config)

    def set_system_config_value(self, name: str, value: Any) -> None:
        """Set one flag at system_config precedence (env still wins)."""
        with self._lock:
            if name not in self._flags:
                raise ValueError(f"unknown system_config key {name!r}")
            self._system_config[name] = value
            self._cache.pop(name, None)

    def get(self, name: str) -> Any:
        with self._lock:
            if name in self._cache:
                return self._cache[name]
            flag = self._flags.get(name)
            if flag is None:
                raise KeyError(f"unknown flag {name!r}")
            env_val = os.environ.get(_ENV_PREFIX + name)
            if env_val is not None:
                value = _PARSERS[flag.type](env_val)
            elif name in self._system_config:
                value = flag.type(self._system_config[name])
            else:
                value = flag.default
            self._cache[name] = value
            return value

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        return self.get(name)

    def reset_cache(self) -> None:
        with self._lock:
            self._cache.clear()

    def all_flags(self) -> Dict[str, _Flag]:
        return dict(self._flags)


GLOBAL_CONFIG = Config()
_D = GLOBAL_CONFIG.declare

# --- core timeouts / intervals (ms unless noted) -----------------------------
_D("health_check_initial_delay_ms", int, 5000, "delay before first node health probe")
_D("health_check_period_ms", int, 1000, "interval between node health probes")
_D("health_check_timeout_ms", int, 5000, "single probe timeout")
_D("health_check_failure_threshold", int, 5, "probes missed before node marked dead")
_D("raylet_report_resources_period_ms", int, 100, "resource gossip interval")
_D("gcs_rpc_server_reconnect_timeout_s", int, 60, "client retry window on GCS restart")
_D("gcs_restart_reconcile_delay_s", float, 2.0,
   "post-restart window for raylets to re-claim actors/bundles before failover")
_D("rpc_schema_validation", bool, True,
   "validate inbound RPCs against the typed wire schemas (rpc/schema.py)")
_D("rpc_retry_base_ms", int, 100, "retryable client initial backoff")
_D("rpc_retry_max_ms", int, 5000, "retryable client max backoff")
_D("rpc_connect_timeout_s", float, 10.0, "client connect timeout")
_D("rpc_require_hello", bool, True,
   "when True (default), a peer that never answers HELLO is treated as a "
   "transport failure (retry/rotate); set False only during a rolling "
   "upgrade from pre-handshake nodes, where the silent peer is assumed "
   "legacy and the connection degrades to protocol 1")
_D("fastloop_enabled", bool, True,
   "C dispatch loop for eligible actor calls and normal tasks "
   "(rpc/native/fastloop.c); falls back to the asyncio path when the "
   "extension can't build")
_D("fast_dispatch_direct", bool, False,
   "caller-thread pushes through cached lease channels (skips the IO"
   " loop per task). Off by default: measured SLOWER under contended"
   " fan-out on this box (the submitting thread and the reply reader"
   " fight for the submitter process's GIL, and breadth-first spread"
   " degrades) — see PERF_PLAN.md round 8; on = lowest per-call latency"
   " for a single isolated submitter")
_D("fast_dispatch_window", int, 4,
   "in-flight pushes per lease on the native task-dispatch channel: >1"
   " overlaps wire/reply latency with execution (small eligible tasks may"
   " then briefly overlap on one leased worker); 1 = strict one-task-per-"
   "lease pacing")

# --- deployment shape --------------------------------------------------------
_D("control_plane_procs", bool, False,
   "multi-process deployment shape: ray_tpu.init() launches the GCS server"
   " and the raylet each in their OWN OS process (own interpreter, own"
   " asyncio loop, own GIL) instead of on the driver's shared IO loop."
   " Removes control-plane/driver loop contention — actor-creation and"
   " lease scheduling no longer time-slice against driver submit/reply"
   " work — at the cost of real RPC hops for every crossing. Off ="
   " the historical in-process head (driver+GCS+raylet share one loop)")
_D("control_plane_ready_timeout_s", float, 40.0,
   "how long init() waits for a spawned GCS/raylet process to print its"
   " READY line before declaring the launch failed")
_D("control_plane_poll_ms", int, 200,
   "supervisor poll interval for detecting GCS/raylet process death in"
   " the multi-process shape")

_D("lease_grant_coalescing", bool, False,
   "burst lease requests ride ONE request_worker_leases RPC (up to"
   " lease_request_batch_size grants, raylet-side fairness cap of half"
   " the currently-fitting copies) instead of one round trip per lease."
   " Off by default: queue depth at submit time OVERSTATES lease demand"
   " under lease retention (most queued tasks drain through reused"
   " leases), so eager multi-grant forks workers the lazy single-lease"
   " ramp never needs — measured 16-60% SLOWER on the multi-client"
   " fan-out rows with it on (PERF_PLAN round 9); the RPC exists for"
   " deployments whose shapes genuinely need N distinct leases at once"
   " (wide gang fan-outs with no retention reuse)")

# --- scheduling --------------------------------------------------------------
_D("scheduler_top_k_fraction", float, 0.2, "hybrid policy: top-k fraction of nodes")
_D("scheduler_top_k_absolute", int, 1, "hybrid policy: min top-k")
_D("scheduler_spread_threshold", float, 0.5, "utilization below which packing wins")
_D("max_pending_lease_requests_per_scheduling_category", int, 10, "")
_D("worker_lease_timeout_ms", int, 30000, "")
_D("lease_request_batch_size", int, 10, "leases requested per shape at once")
_D("lease_idle_grace_ms", int, 100,
   "idle lease retention: how long a drained lease waits for more"
   " same-shape work before returning its worker")

# --- workers -----------------------------------------------------------------
_D("log_to_driver", bool, True,
   "stream worker stdout/stderr to subscribed drivers via GCS pubsub")
_D("worker_log_flush_interval_s", float, 0.2, "worker log relay batch period")
_D("num_prestart_workers", int, 2,
   "warm default-env worker watermark: forked at raylet boot and"
   " replenished concurrently in the background (through the warm"
   " forkserver, once attached) as creations consume the pool")
_D("worker_factory_enabled", bool, True,
   "forkserver worker factory: fork warm interpreters instead of exec")
_D("worker_factory_procs", int, 2,
   "parallel forkserver processes: fork(2) serializes per address space"
   " (~12 ms/fork of a warm interpreter), so K factories raise the"
   " sustained worker-supply — and therefore actor-creation — ceiling")
_D("worker_register_timeout_s", int, 60, "")
_D("worker_raylet_death_check_s", float, 5.0,
   "workers probe their raylet at this interval and exit after 3"
   " consecutive failures — a SIGKILLed raylet (multi-process shape"
   " crash) must not orphan its worker processes forever (0 disables)")
_D("idle_worker_killing_time_threshold_ms", int, 1000, "idle reap threshold")
_D("maximum_startup_concurrency", int, 4, "concurrent worker forks")

# --- object store ------------------------------------------------------------
_D("object_store_memory_bytes", int, 256 * 1024 * 1024, "default shm arena size")
_D("object_store_chunk_size_bytes", int, 5 * 1024 * 1024, "transfer chunk size")
_D("object_pull_max_inflight", int, 8, "concurrent chunks pulled per object")
_D("device_object_cache_entries", int, 32,
   "consumer-side LRU size for resolved remote device objects")
_D("object_spilling_threshold", float, 0.8, "fullness ratio that triggers spill")
_D("object_spilling_dir", str, "", "external storage dir ('' = session dir)")
_D("max_direct_call_object_size", int, 100 * 1024, "inline-in-RPC threshold bytes")
_D("streaming_generator_backpressure", int, 16,
   "max unconsumed streamed items before the owner delays report replies"
   " (0 = unlimited)")
_D("memory_store_max_bytes", int, 512 * 1024 * 1024, "in-process store cap")
_D("transfer_service", bool, True,
   "per-node object transfer service: sealed/spilled objects stream"
   " node-to-node over a dedicated socket server (zero-copy arena views,"
   " no pickle). 0 keeps the legacy per-chunk owner-RPC path as the only"
   " wire path — the parity oracle every multi-node test must also pass")
_D("transfer_chunk_bytes", int, 4 * 1024 * 1024,
   "transfer-service wire granularity: sendall/recv_into window per"
   " slice of the pinned view (tests shrink it to exercise chunking)")
_D("locality_scheduling", bool, True,
   "pick_node prefers the feasible node already holding the largest"
   " total argument bytes (owner-reported location hints), tie-broken"
   " by the configured pack/spread policy — large-arg tasks skip the"
   " wire instead of pulling their args cross-node")

# --- memory / isolation ------------------------------------------------------
_D("memory_monitor_enabled", bool, True, "kill workers before kernel OOM")
_D("memory_usage_threshold", float, 0.95, "node memory fraction that triggers"
   " the OOM killing policy")
_D("memory_monitor_refresh_ms", int, 250, "memory usage poll interval")
_D("cgroup_isolation_enabled", bool, False,
   "place workers in per-worker cgroups with memory limits")

# --- retries / lineage -------------------------------------------------------
_D("max_task_retries", int, 3, "default retries for normal tasks")
_D("actor_max_restarts", int, 0, "default actor restarts")
_D("lineage_pinning_enabled", bool, True, "")
_D("max_lineage_bytes", int, 64 * 1024 * 1024, "lineage buffer cap per worker")

# --- autoscaler --------------------------------------------------------------
_D("autoscaling_enabled", bool, False,
   "queue infeasible-now demands for the autoscaler instead of failing them")
_D("autoscaler_interval_s", float, 1.0, "reconcile loop period")
_D("autoscaler_idle_timeout_s", float, 30.0, "idle node termination threshold")
_D("autoscaler_launch_timeout_s", float, 120.0,
   "drop a launched node that never registers with the GCS within this time")

# --- observability -----------------------------------------------------------
_D("task_events_enabled", bool, True,
   "buffer per-task lifecycle events and flush them to the GCS task store"
   " (reference RAY_task_events_report_interval_ms; 0/off skips the"
   " per-task buffering entirely — read once at worker boot)")
_D("enable_export_api", bool, False,
   "write versioned JSONL export events (actor/node/job/PG transitions)"
   " under <session>/export_events/ for external tooling")

# --- compiled graphs ---------------------------------------------------------
_D("pipeline_overlap", bool, True,
   "overlap channel transfer with stage compute in compiled pipelines:"
   " prefetch reads one item ahead and write-behind outputs on a writer"
   " thread (off = strictly sequential read/compute/write per item)")

# --- collectives -------------------------------------------------------------
_D("quantized_collectives", bool, False,
   "block-wise int8 quantized allreduce/reducescatter"
   " (collective/quantization.py, EQuARX-style per-block scale+offset):"
   " float payloads travel as uint8 codes + per-block scale/offset and are"
   " dequantized-reduced-requantized at each hop (~3.9x fewer bytes on the"
   " wire for f32 at the default block). Off by default: the full-precision"
   " path is the parity oracle every quantized result is bounded against,"
   " and stays bit-identical with the flag off")
_D("quantized_collectives_block", int, 256,
   "quantization block size: elements sharing one (scale, offset) pair;"
   " larger blocks cut scale overhead but widen per-block value range"
   " (looser error bound)")

# --- chaos / testing ---------------------------------------------------------
_D("testing_rpc_failure", str, "", "method=prob fault injection spec, comma-sep")
_D("testing_rpc_failure_seed", int, 0, "deterministic chaos seed")
_D("testing_faults", str, "",
   "deterministic fault-point spec (common/faults.py), comma-separated"
   " point=schedule pairs; same syntax as the RT_FAULTS env var")

# --- TPU ---------------------------------------------------------------------
_D("shm_store_enabled", bool, True, "node-local shared-memory object store")
_D("shm_direct_put_threshold", int, 1 << 20,
   "puts >= this many framed bytes serialize directly into the shm arena"
   " (plasma create/seal; single memcpy)")
_D("oob_arg_threshold", int, 256 * 1024,
   "task/actor args whose pickle-5 out-of-band buffers total >= this many"
   " bytes are written straight into the shm arena and passed by"
   " reference: one memcpy end to end, zero-copy views on the executee"
   " (0 disables; buffer-less or sub-threshold args stay inline)")
_D("memory_store_shm_threshold", int, 1 << 20,
   "in-process store hands byte values >= this size to the node shm"
   " arena (pinned view, zero heap charge) instead of holding them"
   " on-heap (0 disables routing)")
_D("shm_store_bytes", int, 512 * 1024 * 1024, "shm object store capacity")
_D("tpu_chips_per_host", int, 4, "chips exposed per raylet when unprobed")
_D("tpu_topology", str, "", "slice topology label, e.g. v5e-32")

# --- train -------------------------------------------------------------------
_D("train_health_check_interval_s", float, 2.0, "controller poll interval")
_D("train_worker_group_start_timeout_s", float, 120.0, "")
