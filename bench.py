"""Headline benchmark: Llama training-step MFU on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference has no in-tree tokens/sec or MFU numbers (BASELINE.md); the
north-star target from BASELINE.json is >=40% MFU for Llama-family training
on v5e, so ``vs_baseline`` = achieved_MFU / 0.40.
"""

import dataclasses
import json
import sys
import time

# bf16 peak FLOP/s by TPU generation (public spec sheets).
PEAK_FLOPS = {
    "v6": 918e12,   # Trillium
    "v5p": 459e12,
    "v5": 197e12,   # v5e ("TPU v5 lite")
    "v4": 275e12,
    "v3": 123e12,
    "v2": 46e12,
}
CPU_PEAK = 1e12  # nominal, CI fallback only


def peak_flops(device) -> float:
    if device.platform != "tpu":
        return CPU_PEAK
    kind = device.device_kind.lower().replace(" ", "")
    for key in ("v6", "v5p", "v4", "v3", "v2", "v5"):
        if key in kind:
            return PEAK_FLOPS[key]
    return PEAK_FLOPS["v5"]


def _enable_compile_cache():
    """Persistent XLA compilation cache: the 1B-model train step takes
    minutes to compile on a tunneled chip; cached recompiles take
    seconds, so the bench measures the hardware, not the compiler."""
    import os

    import jax

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 — older jax: flag names differ
        pass


def run(config_name: str, batch: int, seq: int, steps: int = 10):
    import os

    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # env var alone is too late when a sitecustomize imported jax
        # first; force the live config too (same dance as conftest.py)
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()

    from ray_tpu.models import llama
    from ray_tpu.models.training import (
        OptimizerConfig, init_train_state, make_train_step)
    from ray_tpu.parallel.mesh import MeshConfig, make_mesh
    from ray_tpu.parallel.sharding import ShardingRules

    cfg = llama.CONFIGS[config_name]
    if jax.default_backend() != "tpu":
        config_name = "debug"  # keep the metric name honest on CI fallback
        cfg, batch, seq, steps = llama.CONFIGS["debug"], 4, 128, 3

    mesh = make_mesh(MeshConfig(dp=1, fsdp=-1), devices=jax.devices()[:1])
    rules = ShardingRules()
    opt = OptimizerConfig(warmup_steps=1, decay_steps=1000).make()

    with jax.sharding.set_mesh(mesh):
        state, _ = init_train_state(
            lambda key: llama.init_params(cfg, key),
            llama.param_logical_axes(cfg), opt, mesh, rules,
            jax.random.key(0))
        step_fn = make_train_step(
            lambda p, b: llama.loss_fn(p, b, cfg, rules), opt, mesh, rules)
        tokens = jax.random.randint(
            jax.random.key(1), (batch, seq), 0, cfg.vocab_size,
            dtype=jnp.int32)
        b = {"tokens": tokens}

        # Sync via host fetch of the loss: the final step's loss depends on
        # the whole chain, and a concrete transfer is a reliable barrier on
        # every backend (block_until_ready is not, on tunneled devices).
        state, m = step_fn(state, b)           # compile + warmup
        float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step_fn(state, b)
        final_loss = float(m["loss"])
        dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    mfu = (cfg.flops_per_token(seq) * tokens_per_sec
           / peak_flops(jax.devices()[0]))
    return {
        "metric": f"llama_{config_name}_train_mfu_1chip",
        "value": round(mfu * 100, 2),
        "unit": "percent_mfu",
        "vs_baseline": round(mfu / 0.40, 3),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "loss": round(final_loss, 4),
        "batch": batch,
        "seq": seq,
        "device": jax.devices()[0].device_kind,
    }


def run_kernels():
    """``--kernels`` mode: flash-attention fwd/bwd + paged-decode
    microbenches — SECONDS, not minutes, so a TPU datum can land even in
    a narrow tunnel-health window when the 1B train step can't
    (round-4 VERDICT ask).  On CPU fallback the shapes shrink and the
    numbers are labeled, never passed off as TPU results."""
    import jax
    import jax.numpy as jnp

    _enable_compile_cache()
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    peak = peak_flops(dev)

    from ray_tpu.ops.attention import flash_attention
    from ray_tpu.ops.pallas.paged_decode_attention import \
        paged_decode_attention

    if on_tpu:
        B, S, H, D = 4, 2048, 16, 128      # 1B-class attention shape
        PB, PLEN, PBS, PKV = 64, 1024, 16, 16
        steps = 20
    else:
        B, S, H, D = 1, 256, 2, 64
        PB, PLEN, PBS, PKV = 2, 64, 16, 2
        steps = 3
    key = jax.random.key(0)
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    q = jax.random.normal(key, (B, S, H, D), dt)
    k = jax.random.normal(key, (B, S, H, D), dt)
    v = jax.random.normal(key, (B, S, H, D), dt)

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(
            jnp.float32).sum()

    fwd = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    fwdbwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    def _time(fn, *args):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / steps

    t_fwd = _time(fwd, q, k, v)
    t_bwd = _time(fwdbwd, q, k, v)
    # causal flash: fwd = 2 matmuls over the lower triangle
    flops_fwd = 4 * B * H * S * S * D * 0.5
    flops_bwd = flops_fwd * 2.5  # dq, dk, dv recompute (standard 2.5x)
    fwd_tflops = flops_fwd / t_fwd / 1e12
    bwd_tflops = flops_bwd / t_bwd / 1e12

    # paged decode: one token per sequence against a block-table KV pool
    MBS = PLEN // PBS
    NBLK = PB * MBS
    qd = jax.random.normal(key, (PB, 1, H, D), dt)
    kp = jax.random.normal(key, (NBLK, PBS, PKV, D), dt)
    vp = jax.random.normal(key, (NBLK, PBS, PKV, D), dt)
    tables = jnp.arange(NBLK, dtype=jnp.int32).reshape(PB, MBS)
    lengths = jnp.full((PB,), PLEN, jnp.int32)
    paged = jax.jit(lambda *a: paged_decode_attention(
        *a, scale=D ** -0.5, interpret=not on_tpu))
    t_dec = _time(paged, qd, kp, vp, tables, lengths)
    # HBM traffic is the decode bottleneck: bytes of KV streamed per step
    kv_bytes = 2 * NBLK * PBS * PKV * D * jnp.dtype(dt).itemsize
    dec_gbps = kv_bytes / t_dec / 1e9

    result = {
        "metric": "kernels_flash_fwd_tflops",
        "value": round(fwd_tflops, 2),
        "unit": "TFLOP/s",
        # kernel-level bar: fraction of chip peak the fwd kernel sustains
        "vs_baseline": round(fwd_tflops * 1e12 / peak, 3),
        "rows": {
            "flash_fwd": {"tflops": round(fwd_tflops, 2),
                          "us": round(t_fwd * 1e6, 1),
                          "shape": [B, S, H, D]},
            "flash_fwd_bwd": {"tflops": round(bwd_tflops, 2),
                              "us": round(t_bwd * 1e6, 1)},
            "paged_decode": {"kv_read_gbps": round(dec_gbps, 1),
                             "us": round(t_dec * 1e6, 1),
                             "batch": PB, "ctx": PLEN},
        },
        "device": dev.device_kind,
    }
    if not on_tpu:
        result["tpu_unavailable"] = "cpu fallback (tiny shapes, interpret)"
        result["vs_baseline"] = 0.0
    return result


def _tpu_responsive(timeout_s: float = 240.0, retries: int = 3):
    """Probe TPU backend init in a SUBPROCESS with a timeout: a wedged
    device tunnel hangs ``jax.devices()`` indefinitely, and a bench that
    never prints its JSON line is worse than a loud CPU fallback.
    Healthy init takes ~20-40s. Retries the probe (a tunnel can be
    transiently down) and returns (ok, reason) so the caller can record
    WHY the TPU was unavailable instead of silently impersonating a
    result (round-2 lesson: BENCH_r02.json recorded a CPU number)."""
    import os
    import subprocess

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return False, "JAX_PLATFORMS=cpu set in environment"
    reason = "unknown"
    for attempt in range(retries):
        try:
            p = subprocess.run(
                [sys.executable, "-c",
                 "import jax; assert jax.devices()"],
                timeout=timeout_s, capture_output=True)
            if p.returncode == 0:
                return True, ""
            reason = (f"probe attempt {attempt + 1}/{retries} exited "
                      f"{p.returncode}: "
                      + p.stderr.decode(errors="replace")[-500:])
        except subprocess.TimeoutExpired:
            reason = (f"probe attempt {attempt + 1}/{retries} timed out "
                      f"after {timeout_s:.0f}s (device tunnel wedged?)")
        print(reason, file=sys.stderr)
        if attempt < retries - 1:  # no pointless backoff after the last try
            time.sleep(min(10.0 * (attempt + 1), 30.0))
    return False, reason


def _last_recorded_tpu_result():
    """The most recent REAL-TPU bench datum committed in-tree
    (BENCH_r*_builder.json, written by the builder when the device
    tunnel was healthy) — surfaced in fallback artifacts so a wedged
    tunnel at bench time doesn't hide the round's actual number."""
    import glob
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    for path in sorted(glob.glob(os.path.join(here,
                                              "BENCH_r*_builder.json"))):
        try:
            with open(path) as f:
                rec = json.loads(f.read().strip().splitlines()[-1])
            if "TPU" in str(rec.get("device", "")):
                best = {"source": os.path.basename(path), **rec}
        except Exception:  # noqa: BLE001
            continue
    return best


def main():
    import os

    if "--kernels" in sys.argv:
        tpu_ok, reason = _tpu_responsive(timeout_s=120.0, retries=2)
        if not tpu_ok:
            os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            result = run_kernels()
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"metric": "kernels_flash_fwd_tflops",
                              "value": 0.0, "unit": "TFLOP/s",
                              "vs_baseline": 0.0,
                              "error": str(e)[:300]}))
            return 1
        if not tpu_ok:
            result["tpu_unavailable"] = reason
        print(json.dumps(result))
        return 0 if tpu_ok else 1

    tpu_ok, tpu_fail_reason = _tpu_responsive()
    if not tpu_ok:
        print("TPU backend unresponsive after retries; running CPU debug "
              "config and exiting non-zero so the driver records the "
              "failure instead of a fake number", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
    # A 1B-param model fits one v5e chip with Adam state; fall back to
    # smaller shapes on memory pressure.
    # batch 16 measured 48.33% MFU vs 47.83% at batch 8 (r4 sweep); both
    # beat the 40% target — the ladder is an OOM fallback, not a search.
    attempts = [("1b_bench", 16, 2048), ("1b_bench", 8, 2048),
                ("1b_bench", 4, 2048), ("tiny", 8, 1024), ("debug", 4, 128)]
    from ray_tpu.models import llama
    # attn_block=1024 measured best on v5e (scripts/mfu_sweep.py: 48.0% MFU
    # at batch 8 vs 43.8% at the 512 default).
    llama.CONFIGS.setdefault(
        "1b_bench",
        dataclasses.replace(llama.CONFIGS["1b"], vocab_size=32000,
                            tie_embeddings=True, max_seq=2048,
                            attn_block=1024))
    last_err = None
    for name, batch, seq in attempts:
        try:
            result = run(name, batch, seq)
            if not tpu_ok:
                # Loud fallback: the number below is a CPU smoke value, not
                # the headline metric. Say so in the artifact and fail —
                # but carry the round's real-TPU datum (recorded when the
                # tunnel was healthy) so the artifact still points at it.
                result["tpu_unavailable"] = tpu_fail_reason
                result["vs_baseline"] = 0.0
                result["last_recorded_tpu_result"] = \
                    _last_recorded_tpu_result()
                print(json.dumps(result))
                return 1
            print(json.dumps(result))
            return 0
        except Exception as e:  # noqa: BLE001 — OOM/compile fallback ladder
            last_err = e
            continue
    print(json.dumps({"metric": "llama_train_mfu_1chip", "value": 0.0,
                      "unit": "percent_mfu", "vs_baseline": 0.0,
                      "tpu_unavailable": tpu_fail_reason or None,
                      "error": str(last_err)[:300]}))
    return 1


if __name__ == "__main__":
    sys.exit(main())
