"""Serve/LLM throughput benchmark (BASELINE target #5 discipline).

Drives the continuous-batching engine (``ray_tpu/serve/llm.py``) directly —
the replica hot path, without HTTP overhead — with a closed-loop client
pool, and reports decode throughput (tokens/s), time-to-first-token, and
slot occupancy as ONE JSON line per config, plus a summary line in the
driver's ``{"metric": ...}`` shape.

On TPU hardware it uses the 1b model config; on CPU fallback it runs the
debug config and marks the artifact accordingly (the same loud-fallback
contract as bench.py — a CPU number is never presented as the headline).
"""

from __future__ import annotations

import json
import sys
import threading
import time


def run_engine_bench(model: str, num_slots: int, n_requests: int,
                     prompt_len: int, max_tokens: int,
                     max_seq: int = 2048) -> dict:
    import numpy as np

    from ray_tpu.serve.llm import LLMEngine

    # bound max_seq: the 1b config's native 8192 would size the KV pool
    # (and the old slot cache alike) past one v5e's HBM at 8 slots
    engine = LLMEngine(model=model, num_slots=num_slots, max_seq=max_seq)
    rng = np.random.default_rng(0)
    vocab = engine.config.vocab_size

    # warmup: compile prefill + decode
    engine.generate(list(rng.integers(1, vocab, size=prompt_len)),
                    max_tokens=4)

    ttfts: list = []
    done_tokens = [0]
    lock = threading.Lock()
    occupancy_samples: list = []

    def client(i):
        prompt = list(rng.integers(1, vocab, size=prompt_len))
        t0 = time.perf_counter()
        rid = engine.submit(prompt, max_tokens=max_tokens)
        first = None
        collected = 0
        while True:
            st = engine.poll(rid)
            collected += len(st["chunks"])
            if first is None and collected:
                first = time.perf_counter() - t0
            if st["done"]:
                break
            time.sleep(0.005)
        with lock:
            ttfts.append(first if first is not None
                         else time.perf_counter() - t0)
            done_tokens[0] += collected

    def sampler(stop):
        while not stop.is_set():
            occupancy_samples.append(
                engine.stats()["active_slots"] / num_slots)
            time.sleep(0.05)

    stop = threading.Event()
    threading.Thread(target=sampler, args=(stop,), daemon=True).start()
    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    stop.set()
    stats = engine.stats()
    engine.shutdown()
    import numpy as np

    return {
        "model": model,
        "num_slots": num_slots,
        "n_requests": n_requests,
        "prompt_len": prompt_len,
        "max_tokens": max_tokens,
        "wall_s": round(dt, 2),
        "decode_tokens_per_s": round(done_tokens[0] / dt, 1),
        "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1000, 1),
        "ttft_p95_ms": round(float(np.percentile(ttfts, 95)) * 1000, 1),
        "slot_occupancy_mean": round(float(np.mean(occupancy_samples)), 3)
        if occupancy_samples else None,
        "engine_steps": stats["steps"],
        "kv_cache": stats.get("kv_cache"),
        "kv_preemptions": stats.get("preemptions"),
    }


def run_chunked_prefill_bench(model: str, long_len: int = 48,
                              chunk: int = 8) -> dict:
    """TTFT interference: p95 TTFT of SHORT requests arriving while LONG
    prompts keep prefilling — chunked vs monolithic prefill. Chunking
    bounds the decode-stall a long prompt inflicts on everyone else."""
    import numpy as np

    from ray_tpu.serve.llm import LLMEngine

    out = {}
    for label, kwargs in (("monolithic", {}),
                          ("chunked", {"prefill_chunk": chunk})):
        engine = LLMEngine(model=model, num_slots=4, kv_cache="slot",
                           **kwargs)
        rng = np.random.default_rng(0)
        vocab = engine.config.vocab_size
        engine.generate(list(rng.integers(1, vocab, size=long_len)),
                        max_tokens=2)  # compile both programs
        engine.generate([1, 2, 3], max_tokens=2)
        ttfts = []
        stop = threading.Event()

        def long_feeder():
            while not stop.is_set():
                engine.generate(
                    list(rng.integers(1, vocab, size=long_len)),
                    max_tokens=2)

        t = threading.Thread(target=long_feeder, daemon=True)
        t.start()
        for _ in range(20):
            t0 = time.perf_counter()
            rid = engine.submit([7, 8, 9], max_tokens=2)
            while not engine.poll(rid)["chunks"]:
                time.sleep(0.001)
            ttfts.append(time.perf_counter() - t0)
        stop.set()
        t.join(timeout=30)
        engine.shutdown()
        out[label] = {
            "short_ttft_p50_ms": round(
                float(np.percentile(ttfts, 50)) * 1000, 1),
            "short_ttft_p95_ms": round(
                float(np.percentile(ttfts, 95)) * 1000, 1),
        }
    out["long_len"] = long_len
    out["prefill_chunk"] = chunk
    return out


def run_speculation_bench(model: str, n_requests: int = 8,
                          prompt_len: int = 24, max_tokens: int = 48,
                          num_slots: int = 4, spec_k: int = 4) -> dict:
    """Spec-vs-baseline decode throughput + acceptance rate, batched
    under continuous batching (same workload, same weights, slot cache
    for all three engines). The draft row shares the target weights —
    an acceptance-rate CEILING with random init; a trained smaller
    draft trades acceptance for cheaper proposal steps."""
    import numpy as np

    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMEngine

    cfg = llama.CONFIGS[model]
    params = llama.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    vocab = cfg.vocab_size
    # half repetitive prompts (prompt-lookup hits), half structureless
    prompts = []
    for i in range(n_requests):
        if i % 2 == 0:
            unit = [int(t) for t in rng.integers(1, vocab, size=4)]
            prompts.append((unit * (prompt_len // 4 + 1))[:prompt_len])
        else:
            prompts.append(
                [int(t) for t in rng.integers(1, vocab, size=prompt_len)])
    configs = (
        ("baseline", {}),
        ("ngram", {"speculation": {"method": "ngram", "k": spec_k}}),
        ("draft", {"speculation": {"method": "draft", "k": spec_k,
                                   "draft_config": cfg,
                                   "draft_params": params}}),
    )
    rows = []
    for label, kw in configs:
        engine = LLMEngine(config=cfg, params=params, num_slots=num_slots,
                           kv_cache="slot", seed=0, **kw)
        # warmup compiles prefill bucket + decode/verify (+ draft)
        # paths: a repetitive prompt guarantees ngram proposals (verify
        # program), a structureless one the no-proposal plain-decode
        # fallback
        unit = [int(t) for t in rng.integers(1, vocab, size=3)]
        engine.generate((unit * prompt_len)[:prompt_len], max_tokens=4)
        engine.generate(
            [int(t) for t in rng.integers(1, vocab, size=prompt_len)],
            max_tokens=4)
        warm = engine.stats()
        t0 = time.perf_counter()
        rids = [engine.submit(p, max_tokens=max_tokens) for p in prompts]
        done = set()
        total = 0
        while len(done) < len(rids):
            for rid in rids:
                if rid in done:
                    continue
                st = engine.poll(rid)
                total += len(st["chunks"])
                if st["done"]:
                    done.add(rid)
            time.sleep(0.002)
        dt = time.perf_counter() - t0
        stats = engine.stats()
        engine.shutdown()
        # deltas over the timed window only — the warmup's repetitive
        # prompt guarantees proposals and would inflate the rate
        proposed = stats["spec_proposed"] - warm["spec_proposed"]
        accepted = stats["spec_accepted"] - warm["spec_accepted"]
        rows.append({
            "speculation": label,
            "decode_tokens_per_s": round(total / dt, 1),
            "acceptance_rate": (round(accepted / proposed, 4)
                                if proposed else None),
            "spec_proposed": proposed,
            "engine_steps": stats["steps"] - warm["steps"],
            "device": jax.default_backend(),
        })
    base = rows[0]["decode_tokens_per_s"]
    for row in rows[1:]:
        row["vs_baseline"] = round(row["decode_tokens_per_s"] / base, 2) \
            if base else None
    return {"model": model, "num_slots": num_slots,
            "n_requests": n_requests, "prompt_len": prompt_len,
            "max_tokens": max_tokens, "spec_k": spec_k, "rows": rows,
            "draft_note": ("draft shares the target weights: acceptance "
                           "ceiling, not a trained-draft speedup claim")}


# --------------------------------------------------------------- proxy/RPS
def _http_keepalive_worker(host: str, port: int, path: str, body: bytes,
                           n_requests: int, latencies: list, errors: list):
    """Closed-loop client on ONE keep-alive connection: send a request,
    read the full response, repeat.  Raw sockets (not urllib) so the
    connection is reused and per-request latency excludes connect cost."""
    import socket

    req = (f"POST {path} HTTP/1.1\r\n"
           f"host: {host}\r\n"
           f"content-length: {len(body)}\r\n"
           f"\r\n").encode() + body
    sock = socket.create_connection((host, port), timeout=60)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        buf = b""
        for _ in range(n_requests):
            t0 = time.perf_counter()
            sock.sendall(req)
            # read one response: headers, then content-length bytes
            while b"\r\n\r\n" not in buf:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ConnectionError("server closed mid-response")
                buf += chunk
            head, _, buf = buf.partition(b"\r\n\r\n")
            clen = 0
            for line in head.split(b"\r\n")[1:]:
                name, _, value = line.partition(b":")
                if name.strip().lower() == b"content-length":
                    clen = int(value.strip())
            while len(buf) < clen:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ConnectionError("server closed mid-body")
                buf += chunk
            buf = buf[clen:]
            if not head.startswith(b"HTTP/1.1 200"):
                raise RuntimeError(head.split(b"\r\n", 1)[0].decode())
            latencies.append(time.perf_counter() - t0)
    except Exception as e:  # noqa: BLE001 — one row, not a crash
        errors.append(repr(e))
    finally:
        sock.close()


def _sse_stream_worker(host: str, port: int, path: str, body: bytes,
                       token_counts: list, errors: list):
    """One SSE stream: POST with Accept: text/event-stream, count data
    events until [DONE]."""
    import socket

    req = (f"POST {path} HTTP/1.1\r\n"
           f"host: {host}\r\n"
           f"accept: text/event-stream\r\n"
           f"content-length: {len(body)}\r\n"
           f"\r\n").encode() + body
    sock = socket.create_connection((host, port), timeout=120)
    try:
        sock.sendall(req)
        buf, tokens, done = b"", 0, False
        while not done:
            chunk = sock.recv(65536)
            if not chunk:
                break
            buf += chunk
            while b"\n" in buf:
                line, _, buf = buf.partition(b"\n")
                line = line.strip()
                if line == b"data: [DONE]":
                    done = True
                elif line.startswith(b"data: "):
                    tokens += 1
        token_counts.append(tokens)
    except Exception as e:  # noqa: BLE001
        errors.append(repr(e))
    finally:
        sock.close()


def run_proxy_bench(conns: int = 8, requests_per_conn: int = 250,
                    handle_clients: int = 4, handle_calls: int = 250,
                    sse_streams: int = 4, sse_rounds: int = 2,
                    sse_tokens: int = 48) -> dict:
    """End-to-end Serve data-plane rows (PERF_PLAN round-11): proxy RPS +
    latency percentiles over keep-alive HTTP against a plain echo
    deployment, a handle-only row (routing cost without HTTP), and SSE
    streaming tokens/s through the LLM debug deployment.

    These are CPU orchestration rows by design: they measure the
    proxy→handle→replica→response path, not model math (the same caption
    discipline as the speculation rows)."""
    import numpy as np

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4, num_tpus=0)
    addr = serve.start(http_port=0, grpc_port=None)
    host, port = addr["http_host"], addr["http_port"]
    rows = []
    try:
        @serve.deployment(name="bench_echo")
        class Echo:
            def __call__(self, request):
                return {"n": len(request.body)}

        serve.run(Echo.bind())
        body = b"x" * 64
        # warmup: route resolution + replica spin-up off the timed path
        warm_lat: list = []
        _http_keepalive_worker(host, port, "/bench_echo", body, 20,
                               warm_lat, [])

        latencies: list = []
        errors: list = []
        threads = [threading.Thread(
            target=_http_keepalive_worker,
            args=(host, port, "/bench_echo", body, requests_per_conn,
                  latencies, errors)) for _ in range(conns)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errors:
            raise RuntimeError(f"proxy bench client errors: {errors[:3]}")
        rows.append({
            "metric": "proxy_rps_plain",
            "value": round(len(latencies) / dt, 1),
            "unit": "requests/s",
            "p50_ms": round(float(np.percentile(latencies, 50)) * 1000, 2),
            "p99_ms": round(float(np.percentile(latencies, 99)) * 1000, 2),
            "conns": conns,
            "requests": len(latencies),
        })

        # handle-only: same replica set, no HTTP — separates routing cost
        # from HTTP parse/render cost
        from ray_tpu.serve.proxy import Request

        handle = serve.get_deployment_handle("bench_echo")
        req = Request(method="POST", path="/bench_echo", query={},
                      headers={}, body=body)
        hl_lat: list = []
        hl_errors: list = []

        def handle_client():
            try:
                for _ in range(handle_calls):
                    t0 = time.perf_counter()
                    ray_tpu.get(handle.remote(req), timeout=60.0)
                    hl_lat.append(time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001
                hl_errors.append(repr(e))

        ray_tpu.get(handle.remote(req), timeout=60.0)  # warm
        threads = [threading.Thread(target=handle_client)
                   for _ in range(handle_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if hl_errors:
            raise RuntimeError(f"handle bench errors: {hl_errors[:3]}")
        rows.append({
            "metric": "handle_calls_per_second",
            "value": round(len(hl_lat) / dt, 1),
            "unit": "calls/s",
            "p50_ms": round(float(np.percentile(hl_lat, 50)) * 1000, 2),
            "p99_ms": round(float(np.percentile(hl_lat, 99)) * 1000, 2),
            "clients": handle_clients,
        })
        serve.delete("bench_echo")

        # SSE streaming: LLM debug deployment, concurrent streams
        from ray_tpu.serve.llm import LLMServer

        dep = serve.deployment(LLMServer, name="bench_llm",
                               max_ongoing_requests=max(4, sse_streams))
        serve.run(dep.bind("debug"), name="bench_llm")
        sse_body = json.dumps({"prompt": [1, 2, 3],
                               "max_tokens": sse_tokens}).encode()
        # warmup compiles prefill/decode
        _sse_stream_worker(host, port, "/bench_llm", sse_body, [], [])
        counts: list = []
        sse_errors: list = []
        t0 = time.perf_counter()
        for _ in range(sse_rounds):
            threads = [threading.Thread(
                target=_sse_stream_worker,
                args=(host, port, "/bench_llm", sse_body, counts,
                      sse_errors)) for _ in range(sse_streams)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        dt = time.perf_counter() - t0
        if sse_errors:
            raise RuntimeError(f"sse bench errors: {sse_errors[:3]}")
        rows.append({
            "metric": "sse_tokens_per_second",
            "value": round(sum(counts) / dt, 1),
            "unit": "tokens/s",
            "streams": sse_streams,
            "rounds": sse_rounds,
            "tokens_per_stream": sse_tokens,
        })
        serve.delete("bench_llm")

        # per-stage accounting from the proxy, when it exports it
        try:
            proxy = ray_tpu.get_actor("SERVE_PROXY")
            dbg = ray_tpu.get([proxy.debug_state.remote()], timeout=10.0)[0]
        except Exception:  # noqa: BLE001 — pre-round-11 proxy
            dbg = None
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
    return {"results": rows, "proxy_debug_state": dbg}


# ---------------------------------------------------------- overload/chaos
def _typed_fire(url: str, out: list, lock) -> None:
    """One request on its own connection; append (status, latency_s).
    Typed HTTP errors (429/503) are answers; anything untyped records
    status 0 — the caller fails the bench on those."""
    import urllib.error
    import urllib.request

    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(
                urllib.request.Request(url, data=b"x"), timeout=60) as resp:
            resp.read()
            status = resp.status
    except urllib.error.HTTPError as e:
        status = e.code
    except Exception:  # noqa: BLE001 — untyped answer: counted, then fatal
        status = 0
    with lock:
        out.append((status, time.perf_counter() - t0))


def run_overload_bench(burst_factor: float = 3.0, burst_s: float = 3.0,
                       service_s: float = 0.3,
                       failover_window_s: float = 8.0) -> dict:
    """Overload + failover rows (ISSUE 18): the robustness claims as
    guarded numbers.

    - ``proxy_overload_accepted_rps``: open-loop burst at ~burst_factor×
      replica capacity against a fixed-service-time app.  Admission
      control must answer EVERY request — 200 for the capacity's worth,
      typed 503/429 before dispatch for the excess — and accepted
      requests keep their latency profile (p99_accepted vs p99_unloaded).
    - ``proxy_failover_rps_recovered``: steady closed-loop load over two
      replicas, one SIGKILLed mid-window with ``serve.replica.call``
      armed (nth:40) in the replica workers, so the row is measured
      THROUGH an injected transport fault, not just a clean kill.  Pins
      post-recovery RPS plus the typed error window and respawn time.

    An unanswered or untyped (non-200/429/503) response raises — these
    rows exist so 'never hang, never an untyped 5xx' is a regression the
    guard can catch."""
    import os
    import signal

    import numpy as np

    import ray_tpu
    from ray_tpu import serve

    os.environ.setdefault("RT_FAULTS", "serve.replica.call=nth:40")
    ray_tpu.init(num_cpus=4, num_tpus=0)
    addr = serve.start(http_port=0, grpc_port=None)
    host, port = addr["http_host"], addr["http_port"]
    rows = []
    lock = threading.Lock()
    try:
        @serve.deployment(name="bench_overload", num_replicas=2,
                          max_ongoing_requests=4)
        class Work:
            def __call__(self, request):
                time.sleep(service_s)
                return "ok"

        serve.run(Work.bind())
        url = f"http://{host}:{port}/bench_overload"

        # unloaded profile: sequential requests, zero contention
        unloaded: list = []
        for _ in range(12):
            _typed_fire(url, unloaded, lock)
        bad = [s for s, _ in unloaded if s != 200]
        if bad:
            raise RuntimeError(f"unloaded warmup saw non-200s: {bad}")
        p99_unloaded = float(np.percentile([l for _, l in unloaded], 99))

        # open-loop burst at ~burst_factor × capacity: fire on the
        # schedule, never wait for responses — overload by construction
        capacity_rps = (2 * 4) / service_s  # replicas × slots / service
        offered_rps = burst_factor * capacity_rps
        n_total = int(offered_rps * burst_s)
        results: list = []
        threads = []
        t0 = time.perf_counter()
        for i in range(n_total):
            delay = (t0 + i / offered_rps) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=_typed_fire,
                                 args=(url, results, lock))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=120)
        wall = time.perf_counter() - t0
        if len(results) != n_total:
            raise RuntimeError(
                f"overload burst: {n_total - len(results)} of {n_total} "
                "requests never answered — the proxy hung under overload")
        untyped = [s for s, _ in results if s not in (200, 429, 503)]
        if untyped:
            raise RuntimeError(
                f"overload burst: untyped responses {untyped[:5]} — "
                "every shed must be a typed 429/503")
        accepted = [l for s, l in results if s == 200]
        if not accepted:
            raise RuntimeError("overload burst: nothing accepted")
        rows.append({
            "metric": "proxy_overload_accepted_rps",
            "value": round(len(accepted) / wall, 1),
            "unit": "requests/s",
            "offered_rps": round(offered_rps, 1),
            "burst_s": round(wall, 2),
            "requests": n_total,
            "shed_pct": round(
                100.0 * (n_total - len(accepted)) / n_total, 1),
            "p99_accepted_ms": round(
                float(np.percentile(accepted, 99)) * 1000, 1),
            "p99_unloaded_ms": round(p99_unloaded * 1000, 1),
            "service_time_ms": service_s * 1000,
        })
        serve.delete("bench_overload")

        # failover: SIGKILL one of two replicas under steady load
        @serve.deployment(name="bench_failover", num_replicas=2,
                          max_ongoing_requests=8)
        class Fast:
            def __call__(self, request):
                return "ok"

        serve.run(Fast.bind())
        furl = f"http://{host}:{port}/bench_failover"
        warm: list = []
        for _ in range(10):
            _typed_fire(furl, warm, lock)
        samples: list = []  # (t_rel, status)
        stop = threading.Event()
        slock = threading.Lock()
        bench_t0 = time.perf_counter()

        def steady_client():
            while not stop.is_set():
                one: list = []
                olock = threading.Lock()
                t_sent = time.perf_counter() - bench_t0
                _typed_fire(furl, one, olock)
                with slock:
                    samples.append((t_sent, one[0][0]))

        clients = [threading.Thread(target=steady_client)
                   for _ in range(4)]
        for c in clients:
            c.start()
        time.sleep(failover_window_s * 0.3)
        ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
        _, replicas, _, _ = ray_tpu.get(
            [ctrl.get_replicas.remote("bench_failover")], timeout=10)[0]
        victim_pid = ray_tpu.get([replicas[0].pid.remote()], timeout=10)[0]
        if victim_pid in (os.getpid(), os.getppid()):
            raise RuntimeError("refusing to SIGKILL the driver")
        from ray_tpu.common.status import ActorDiedError

        t_kill = time.perf_counter() - bench_t0
        os.kill(victim_pid, signal.SIGKILL)
        recovery_s = None
        deadline = time.perf_counter() + 60
        try:
            while time.perf_counter() < deadline:
                # the controller's view holds the corpse until its next
                # probe cycle: pinging it raises — keep polling
                try:
                    _, reps, _, _ = ray_tpu.get(
                        [ctrl.get_replicas.remote("bench_failover")],
                        timeout=10)[0]
                    pids = (ray_tpu.get([r.pid.remote() for r in reps],
                                        timeout=5)
                            if len(reps) == 2 else [])
                except (ActorDiedError, ConnectionError, TimeoutError):
                    pids = []
                if pids and victim_pid not in pids:
                    recovery_s = time.perf_counter() - bench_t0 - t_kill
                    break
                time.sleep(0.1)
            remaining = failover_window_s - (time.perf_counter() - bench_t0)
            if remaining > 0:
                time.sleep(remaining)
        finally:
            stop.set()  # clients must stop even when the poll raises
        for c in clients:
            c.join(timeout=120)
        if recovery_s is None:
            raise RuntimeError("failover: replica never respawned")
        with slock:
            data = list(samples)
        untyped = [(t, s) for t, s in data if s not in (200, 429, 503)]
        if untyped:
            raise RuntimeError(f"failover: untyped responses "
                               f"{untyped[:5]} — replica death must "
                               "surface as retry-to-200 or typed shed")
        errs = [t for t, s in data if s != 200]
        pre = [t for t, s in data if s == 200 and t < t_kill]
        post_start = t_kill + recovery_s
        post = [t for t, s in data if s == 200 and t >= post_start]
        post_span = (time.perf_counter() - bench_t0) - post_start
        rows.append({
            "metric": "proxy_failover_rps_recovered",
            "value": round(len(post) / post_span, 1)
            if post_span > 0 else 0.0,
            "unit": "requests/s",
            "pre_kill_rps": round(len(pre) / t_kill, 1),
            "error_window_s": round(max(errs) - min(errs), 3)
            if errs else 0.0,
            "recovery_s": round(recovery_s, 2),
            "typed_errors": len(errs),
            "untyped_errors": 0,
            "clients": 4,
            "rt_faults": os.environ.get("RT_FAULTS"),
        })
        serve.delete("bench_failover")
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
    return {"results": rows}


def run_prefix_bench(model: str = "tiny", num_slots: int = 4,
                     n_requests: int = 20, shared_frac: float = 0.8,
                     prefix_len: int = 448, tail_len: int = 16,
                     max_tokens: int = 8, kv_block_size: int = 64,
                     max_seq: int = 1024) -> dict:
    """Shared-prefix traffic (ISSUE 19 acceptance shape): 80% of the
    requests agree on a ``prefix_len``-token system prompt and diverge
    only in a ``tail_len``-token tail; the other 20% are unrelated.
    The same sequential closed loop runs twice — ``prefix_cache="off"``
    (every request pays the full monolithic prefill) vs
    ``prefix_cache="radix"`` (a hit adopts the cached blocks and
    prefills ONLY the suffix) — and the rows report the TTFT ratio and
    decode throughput. Sequential on purpose: one request in flight
    isolates the prefill term of TTFT, which is the thing radix reuse
    changes; under concurrency TTFT is queueing-dominated and the same
    compute saving hides in scheduling noise. Greedy parity is asserted
    in-bench: the radix engine must emit byte-identical token streams,
    or the bench raises instead of reporting a number."""
    import numpy as np

    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMEngine

    rng = np.random.default_rng(7)
    vocab = llama.CONFIGS[model].vocab_size
    prefix = [int(t) for t in rng.integers(1, vocab, size=prefix_len)]
    n_shared = int(n_requests * shared_frac)
    prompts = []
    for i in range(n_requests):
        tail = [int(t) for t in rng.integers(1, vocab, size=tail_len)]
        if i < n_shared:
            prompts.append(prefix + tail)
        else:
            prompts.append([int(t) for t in rng.integers(
                1, vocab, size=prefix_len)] + tail)
    order = [int(i) for i in rng.permutation(n_requests)]
    # fixed warmup tails (drawn outside the per-engine loop so both
    # engines see identical token streams): wt1 compiles the monolithic
    # prefill + decode programs, wt2 hits the radix tree wt1 populated
    # and compiles the suffix-chunk kernel — all compile cost off the
    # clock, and the timed radix hits measure steady state
    wt1 = [max(1, vocab - 2)] * tail_len
    wt2 = [max(1, vocab - 3)] * tail_len

    out = {}
    for label, kw in (("cold", {"prefix_cache": "off"}),
                      ("radix", {"prefix_cache": "radix"})):
        eng = LLMEngine(model=model, num_slots=num_slots, max_seq=max_seq,
                        kv_block_size=kv_block_size, seed=0, **kw)
        for wt in (wt1, wt2):
            eng.generate(prefix + wt, max_tokens=2)
        ttfts: list = [None] * n_requests
        outs: list = [None] * n_requests

        t0 = time.perf_counter()
        for i in order:
            tr = time.perf_counter()
            rid = eng.submit(prompts[i], max_tokens=max_tokens)
            first, chunks = None, []
            while True:
                st = eng.poll(rid)
                chunks.extend(st["chunks"])
                if first is None and chunks:
                    first = time.perf_counter() - tr
                if st["done"]:
                    break
                time.sleep(0.0005)
            ttfts[i] = (first if first is not None
                        else time.perf_counter() - tr)
            outs[i] = chunks
        wall = time.perf_counter() - t0
        stats = eng.stats()
        eng.shutdown()
        pc = stats.get("prefix_cache", {})
        out[label] = {
            "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1000,
                                 1),
            "ttft_p95_ms": round(float(np.percentile(ttfts, 95)) * 1000,
                                 1),
            "tokens_per_s": round(sum(len(o) for o in outs) / wall, 1),
            "wall_s": round(wall, 2),
            "outputs": outs,
            "prefix_hits": stats.get("prefix_hits", 0),
            "hit_tokens": pc.get("hit_tokens", 0),
            "cow_hits": pc.get("cow_hits", 0),
        }

    bad = [i for i in range(n_requests)
           if out["radix"]["outputs"][i] != out["cold"]["outputs"][i]]
    if bad:
        raise RuntimeError(
            f"greedy parity violated on requests {bad[:5]}: radix reuse "
            "must be bit-identical to cold prefill")
    cold, radix = out["cold"], out["radix"]
    speedup = (round(cold["ttft_p50_ms"] / radix["ttft_p50_ms"], 2)
               if radix["ttft_p50_ms"] > 0 else float("inf"))
    if speedup < 2.0:
        raise RuntimeError(
            f"prefix-cache TTFT speedup {speedup}x < 2x acceptance "
            f"(cold p50 {cold['ttft_p50_ms']}ms, radix p50 "
            f"{radix['ttft_p50_ms']}ms)")
    common = {
        "model": model, "num_slots": num_slots, "n_requests": n_requests,
        "shared_frac": shared_frac, "prefix_len": prefix_len,
        "tail_len": tail_len, "max_tokens": max_tokens,
        "greedy_parity": True,
        "device": jax.devices()[0].platform,
    }
    rows = [
        dict(common,
             metric="llm_prefix_ttft_speedup", value=speedup, unit="x",
             ttft_p50_cold_ms=cold["ttft_p50_ms"],
             ttft_p50_radix_ms=radix["ttft_p50_ms"],
             ttft_p95_cold_ms=cold["ttft_p95_ms"],
             ttft_p95_radix_ms=radix["ttft_p95_ms"],
             prefix_hits=radix["prefix_hits"],
             hit_tokens=radix["hit_tokens"],
             cow_hits=radix["cow_hits"]),
        dict(common,
             metric="llm_prefix_decode_tokens_per_s",
             value=radix["tokens_per_s"], unit="tokens/s",
             cold_tokens_per_s=cold["tokens_per_s"],
             wall_radix_s=radix["wall_s"], wall_cold_s=cold["wall_s"]),
    ]
    return {"results": rows}


PROXY_CAPTION = (
    "proxy rows are CPU orchestration cost by design (PERF_PLAN round-11): "
    "they measure the proxy→handle→replica→response path end to end — "
    "RPS/latency of the HTTP data plane, not model math. "
    "handle_calls_per_second is the same replica set without HTTP, "
    "separating routing cost from parse/render cost. before_round11 = "
    "same-box numbers at the pre-async-data-plane commit (threadpool "
    "dispatch, blocking gets, poll-based SSE); the round-11 values ride "
    "the async-native path (get_async + micro-batched dispatch + "
    "push-based SSE). sse_tokens_per_second is engine-rate-bound on this "
    "1-core CPU box — the round-11 win there is protocol shape (push, "
    "no poll RPCs), not throughput. "
    "proxy_overload_accepted_rps (round-18, --overload) drives an "
    "open-loop burst at ~3x replica capacity: value is the RPS of "
    "ACCEPTED (200) requests, shed_pct the fraction answered with a "
    "typed 503/429 BEFORE dispatch, p99_accepted_ms vs p99_unloaded_ms "
    "the latency-protection claim. proxy_failover_rps_recovered "
    "SIGKILLs one of two replicas under steady load with "
    "serve.replica.call armed (nth:40) in the replica workers: value is "
    "post-recovery RPS; error_window_s / recovery_s bound the typed "
    "error window and respawn. both chaos rows raise on any unanswered "
    "or untyped (non-200/429/503) response. "
    "llm_prefix_ttft_speedup / llm_prefix_decode_tokens_per_s "
    "(round-19, --prefix) drive 80%-shared-prefix traffic at the engine "
    "twice — prefix_cache=off vs radix block reuse — on the same "
    "sequential closed loop (one request in flight isolates the prefill "
    "term of TTFT, the thing radix reuse changes): value is cold/radix "
    "TTFT p50 (acceptance >= 2x, asserted in-bench) and radix tokens/s; "
    "greedy parity (radix streams bit-identical to cold) is asserted "
    "before any row is written.")


def _merge_proxy_section(proxy: dict) -> None:
    """Write the proxy rows into BENCH_serve.json, preserving the other
    sections and any per-row history fields (before_round11) the fresh
    rows don't carry.  The row-merge rule is bench_guard's — imported,
    not re-implemented, so --capture and --proxy can never diverge."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "rt_bench_guard", os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "scripts", "bench_guard.py"))
    bench_guard = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_guard)

    doc = {}
    if os.path.exists("BENCH_serve.json"):
        with open("BENCH_serve.json") as f:
            doc = json.load(f)
    old_proxy = doc.get("proxy", {})
    old_rows = {r.get("metric"): r for r in old_proxy.get("results", [])}
    proxy = dict(proxy)
    fresh_rows = proxy.get("results", [])
    fresh_metrics = {r.get("metric") for r in fresh_rows}
    merged = bench_guard._merge_rows(fresh_rows, old_rows)
    # --proxy and --overload write DISJOINT row sets into one section:
    # rows this invocation never measures must survive the merge
    merged += [row for m, row in old_rows.items() if m not in fresh_metrics]
    proxy["results"] = merged
    for k, v in old_proxy.items():  # section keys this run lacks
        proxy.setdefault(k, v)
    proxy["caption"] = PROXY_CAPTION
    doc["proxy"] = proxy
    with open("BENCH_serve.json", "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def main():
    # reuse bench.py's loud TPU-vs-CPU contract
    from bench import _tpu_responsive

    if "--proxy" in sys.argv:
        # proxy/data-plane rows only: CPU orchestration cost, valid on any
        # box (the captioned contract above)
        proxy = run_proxy_bench()
        _merge_proxy_section(proxy)
        print(json.dumps(proxy["results"], indent=1))
        return 0

    if "--prefix" in sys.argv:
        # shared-prefix radix-reuse rows: engine-level (no HTTP), greedy
        # parity + the >=2x TTFT acceptance asserted inside; merged into
        # the proxy section so bench_guard's --fresh-serve diff sees them
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        section = run_prefix_bench()
        _merge_proxy_section(section)
        print(json.dumps(section["results"], indent=1))
        return 0

    if "--overload" in sys.argv:
        # overload shed + SIGKILL failover chaos rows: answered-typed is
        # asserted inside; merged into the proxy section next to the
        # plain RPS rows
        section = run_overload_bench()
        _merge_proxy_section(section)
        print(json.dumps(section["results"], indent=1))
        return 0

    tpu_ok, reason = _tpu_responsive()
    import os

    if not tpu_ok:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        model, slots, n_req, plen, mtok = "debug", 8, 16, 32, 32
    else:
        model, slots, n_req, plen, mtok = "1b", 8, 24, 128, 128

    result = run_engine_bench(model, slots, n_req, plen, mtok)
    result["chunked_prefill_interference"] = run_chunked_prefill_bench(
        model, long_len=max(48, plen), chunk=max(8, plen // 4))
    result["speculation"] = run_speculation_bench(
        model, prompt_len=min(24, plen), max_tokens=mtok)
    if not tpu_ok:
        result["tpu_unavailable"] = reason
    print(json.dumps(result))
    headline = {
        "metric": f"llm_serve_{result['model']}_decode_tokens_per_s",
        "value": result["decode_tokens_per_s"],
        "unit": "tokens/s",
        "vs_baseline": None,  # no reference serve-throughput number in-tree
        "ttft_p50_ms": result["ttft_p50_ms"],
        "slot_occupancy_mean": result["slot_occupancy_mean"],
    }
    if not tpu_ok:
        headline["tpu_unavailable"] = reason
    print(json.dumps(headline))
    import os as _os

    if _os.path.exists("BENCH_serve.json"):
        # keep the proxy/data-plane section (written by --proxy runs):
        # the engine rows and the proxy rows are separate measurements
        with open("BENCH_serve.json") as f:
            prev = json.load(f)
        if "proxy" in prev:
            result["proxy"] = prev["proxy"]
    with open("BENCH_serve.json", "w") as f:
        json.dump(result, f, indent=1)
    return 0 if tpu_ok else 1


if __name__ == "__main__":
    sys.exit(main())
