"""Serve/LLM throughput benchmark (BASELINE target #5 discipline).

Drives the continuous-batching engine (``ray_tpu/serve/llm.py``) directly —
the replica hot path, without HTTP overhead — with a closed-loop client
pool, and reports decode throughput (tokens/s), time-to-first-token, and
slot occupancy as ONE JSON line per config, plus a summary line in the
driver's ``{"metric": ...}`` shape.

On TPU hardware it uses the 1b model config; on CPU fallback it runs the
debug config and marks the artifact accordingly (the same loud-fallback
contract as bench.py — a CPU number is never presented as the headline).
"""

from __future__ import annotations

import json
import sys
import threading
import time


def run_engine_bench(model: str, num_slots: int, n_requests: int,
                     prompt_len: int, max_tokens: int,
                     max_seq: int = 2048) -> dict:
    import numpy as np

    from ray_tpu.serve.llm import LLMEngine

    # bound max_seq: the 1b config's native 8192 would size the KV pool
    # (and the old slot cache alike) past one v5e's HBM at 8 slots
    engine = LLMEngine(model=model, num_slots=num_slots, max_seq=max_seq)
    rng = np.random.default_rng(0)
    vocab = engine.config.vocab_size

    # warmup: compile prefill + decode
    engine.generate(list(rng.integers(1, vocab, size=prompt_len)),
                    max_tokens=4)

    ttfts: list = []
    done_tokens = [0]
    lock = threading.Lock()
    occupancy_samples: list = []

    def client(i):
        prompt = list(rng.integers(1, vocab, size=prompt_len))
        t0 = time.perf_counter()
        rid = engine.submit(prompt, max_tokens=max_tokens)
        first = None
        collected = 0
        while True:
            st = engine.poll(rid)
            collected += len(st["chunks"])
            if first is None and collected:
                first = time.perf_counter() - t0
            if st["done"]:
                break
            time.sleep(0.005)
        with lock:
            ttfts.append(first if first is not None
                         else time.perf_counter() - t0)
            done_tokens[0] += collected

    def sampler(stop):
        while not stop.is_set():
            occupancy_samples.append(
                engine.stats()["active_slots"] / num_slots)
            time.sleep(0.05)

    stop = threading.Event()
    threading.Thread(target=sampler, args=(stop,), daemon=True).start()
    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    stop.set()
    stats = engine.stats()
    engine.shutdown()
    import numpy as np

    return {
        "model": model,
        "num_slots": num_slots,
        "n_requests": n_requests,
        "prompt_len": prompt_len,
        "max_tokens": max_tokens,
        "wall_s": round(dt, 2),
        "decode_tokens_per_s": round(done_tokens[0] / dt, 1),
        "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1000, 1),
        "ttft_p95_ms": round(float(np.percentile(ttfts, 95)) * 1000, 1),
        "slot_occupancy_mean": round(float(np.mean(occupancy_samples)), 3)
        if occupancy_samples else None,
        "engine_steps": stats["steps"],
        "kv_cache": stats.get("kv_cache"),
        "kv_preemptions": stats.get("preemptions"),
    }


def run_chunked_prefill_bench(model: str, long_len: int = 48,
                              chunk: int = 8) -> dict:
    """TTFT interference: p95 TTFT of SHORT requests arriving while LONG
    prompts keep prefilling — chunked vs monolithic prefill. Chunking
    bounds the decode-stall a long prompt inflicts on everyone else."""
    import numpy as np

    from ray_tpu.serve.llm import LLMEngine

    out = {}
    for label, kwargs in (("monolithic", {}),
                          ("chunked", {"prefill_chunk": chunk})):
        engine = LLMEngine(model=model, num_slots=4, kv_cache="slot",
                           **kwargs)
        rng = np.random.default_rng(0)
        vocab = engine.config.vocab_size
        engine.generate(list(rng.integers(1, vocab, size=long_len)),
                        max_tokens=2)  # compile both programs
        engine.generate([1, 2, 3], max_tokens=2)
        ttfts = []
        stop = threading.Event()

        def long_feeder():
            while not stop.is_set():
                engine.generate(
                    list(rng.integers(1, vocab, size=long_len)),
                    max_tokens=2)

        t = threading.Thread(target=long_feeder, daemon=True)
        t.start()
        for _ in range(20):
            t0 = time.perf_counter()
            rid = engine.submit([7, 8, 9], max_tokens=2)
            while not engine.poll(rid)["chunks"]:
                time.sleep(0.001)
            ttfts.append(time.perf_counter() - t0)
        stop.set()
        t.join(timeout=30)
        engine.shutdown()
        out[label] = {
            "short_ttft_p50_ms": round(
                float(np.percentile(ttfts, 50)) * 1000, 1),
            "short_ttft_p95_ms": round(
                float(np.percentile(ttfts, 95)) * 1000, 1),
        }
    out["long_len"] = long_len
    out["prefill_chunk"] = chunk
    return out


def run_speculation_bench(model: str, n_requests: int = 8,
                          prompt_len: int = 24, max_tokens: int = 48,
                          num_slots: int = 4, spec_k: int = 4) -> dict:
    """Spec-vs-baseline decode throughput + acceptance rate, batched
    under continuous batching (same workload, same weights, slot cache
    for all three engines). The draft row shares the target weights —
    an acceptance-rate CEILING with random init; a trained smaller
    draft trades acceptance for cheaper proposal steps."""
    import numpy as np

    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMEngine

    cfg = llama.CONFIGS[model]
    params = llama.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    vocab = cfg.vocab_size
    # half repetitive prompts (prompt-lookup hits), half structureless
    prompts = []
    for i in range(n_requests):
        if i % 2 == 0:
            unit = [int(t) for t in rng.integers(1, vocab, size=4)]
            prompts.append((unit * (prompt_len // 4 + 1))[:prompt_len])
        else:
            prompts.append(
                [int(t) for t in rng.integers(1, vocab, size=prompt_len)])
    configs = (
        ("baseline", {}),
        ("ngram", {"speculation": {"method": "ngram", "k": spec_k}}),
        ("draft", {"speculation": {"method": "draft", "k": spec_k,
                                   "draft_config": cfg,
                                   "draft_params": params}}),
    )
    rows = []
    for label, kw in configs:
        engine = LLMEngine(config=cfg, params=params, num_slots=num_slots,
                           kv_cache="slot", seed=0, **kw)
        # warmup compiles prefill bucket + decode/verify (+ draft)
        # paths: a repetitive prompt guarantees ngram proposals (verify
        # program), a structureless one the no-proposal plain-decode
        # fallback
        unit = [int(t) for t in rng.integers(1, vocab, size=3)]
        engine.generate((unit * prompt_len)[:prompt_len], max_tokens=4)
        engine.generate(
            [int(t) for t in rng.integers(1, vocab, size=prompt_len)],
            max_tokens=4)
        warm = engine.stats()
        t0 = time.perf_counter()
        rids = [engine.submit(p, max_tokens=max_tokens) for p in prompts]
        done = set()
        total = 0
        while len(done) < len(rids):
            for rid in rids:
                if rid in done:
                    continue
                st = engine.poll(rid)
                total += len(st["chunks"])
                if st["done"]:
                    done.add(rid)
            time.sleep(0.002)
        dt = time.perf_counter() - t0
        stats = engine.stats()
        engine.shutdown()
        # deltas over the timed window only — the warmup's repetitive
        # prompt guarantees proposals and would inflate the rate
        proposed = stats["spec_proposed"] - warm["spec_proposed"]
        accepted = stats["spec_accepted"] - warm["spec_accepted"]
        rows.append({
            "speculation": label,
            "decode_tokens_per_s": round(total / dt, 1),
            "acceptance_rate": (round(accepted / proposed, 4)
                                if proposed else None),
            "spec_proposed": proposed,
            "engine_steps": stats["steps"] - warm["steps"],
            "device": jax.default_backend(),
        })
    base = rows[0]["decode_tokens_per_s"]
    for row in rows[1:]:
        row["vs_baseline"] = round(row["decode_tokens_per_s"] / base, 2) \
            if base else None
    return {"model": model, "num_slots": num_slots,
            "n_requests": n_requests, "prompt_len": prompt_len,
            "max_tokens": max_tokens, "spec_k": spec_k, "rows": rows,
            "draft_note": ("draft shares the target weights: acceptance "
                           "ceiling, not a trained-draft speedup claim")}


def main():
    # reuse bench.py's loud TPU-vs-CPU contract
    from bench import _tpu_responsive

    tpu_ok, reason = _tpu_responsive()
    import os

    if not tpu_ok:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        model, slots, n_req, plen, mtok = "debug", 8, 16, 32, 32
    else:
        model, slots, n_req, plen, mtok = "1b", 8, 24, 128, 128

    result = run_engine_bench(model, slots, n_req, plen, mtok)
    result["chunked_prefill_interference"] = run_chunked_prefill_bench(
        model, long_len=max(48, plen), chunk=max(8, plen // 4))
    result["speculation"] = run_speculation_bench(
        model, prompt_len=min(24, plen), max_tokens=mtok)
    if not tpu_ok:
        result["tpu_unavailable"] = reason
    print(json.dumps(result))
    headline = {
        "metric": f"llm_serve_{result['model']}_decode_tokens_per_s",
        "value": result["decode_tokens_per_s"],
        "unit": "tokens/s",
        "vs_baseline": None,  # no reference serve-throughput number in-tree
        "ttft_p50_ms": result["ttft_p50_ms"],
        "slot_occupancy_mean": result["slot_occupancy_mean"],
    }
    if not tpu_ok:
        headline["tpu_unavailable"] = reason
    print(json.dumps(headline))
    with open("BENCH_serve.json", "w") as f:
        json.dump(result, f, indent=1)
    return 0 if tpu_ok else 1


if __name__ == "__main__":
    sys.exit(main())
